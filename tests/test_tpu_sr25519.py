"""TPU batched sr25519 — bit-identical parity with the CPU verifier.

The third curve kernel (crypto/tpu/sr25519_batch.py): ristretto decode,
joint Straus s·B + k·(−A) on the shared ed25519 machinery, ristretto
equality. Accept/reject must match crypto/sr25519.py exactly. Runs on
the virtual CPU platform (conftest.py).
"""

import numpy as np

from cometbft_tpu.crypto import sr25519 as sr
from cometbft_tpu.crypto.tpu import sr25519_batch


def _cpu_verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    return sr.PubKeySr25519(pk).verify_signature(msg, sig)


def _assert_parity(pks, msgs, sigs):
    got = sr25519_batch.verify_batch(pks, msgs, sigs)
    want = [_cpu_verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    assert got == want, f"mismatch: tpu={got} cpu={want}"
    return got


class TestSr25519Parity:
    def test_valid_corrupted_and_cross(self):
        keys = [sr.PrivKeySr25519(bytes([i]) * 32) for i in range(1, 6)]
        pks, msgs, sigs = [], [], []
        for i, k in enumerate(keys):
            m = b"sr vote %d" % i
            s = bytearray(k.sign(m))
            if i == 1:
                s[8] ^= 1  # corrupt R
            if i == 3:
                s[40] ^= 1  # corrupt s
            pks.append(k.pub_key().bytes())
            msgs.append(m)
            sigs.append(bytes(s))
        # wrong key for a valid signature
        pks.append(keys[0].pub_key().bytes())
        msgs.append(b"sr vote 4")
        sigs.append(keys[4].sign(b"sr vote 4"))
        got = _assert_parity(pks, msgs, sigs)
        assert got[0] and not got[1] and not got[3] and not got[5]

    def test_format_bit_and_scalar_range(self):
        k = sr.PrivKeySr25519(b"\x07" * 32)
        m = b"fmt"
        sig = k.sign(m)
        # clearing the schnorrkel format bit must reject
        old_fmt = sig[:63] + bytes([sig[63] & 0x7F])
        # s >= L (set high bits below the format bit)
        fat_s = sig[:32] + b"\xff" * 31 + bytes([0xFF])
        got = _assert_parity(
            [k.pub_key().bytes()] * 3, [m] * 3, [sig, old_fmt, fat_s]
        )
        assert got == [True, False, False]

    def test_non_canonical_and_odd_encodings(self):
        k = sr.PrivKeySr25519(b"\x09" * 32)
        m = b"enc"
        sig = k.sign(m)
        odd_pk = bytearray(k.pub_key().bytes())
        odd_pk[0] |= 1  # "negative" ristretto encoding
        non_canon = b"\xff" * 32  # >= p
        odd_r = bytearray(sig)
        odd_r[0] |= 1  # "negative" R encoding
        fat_r = b"\xff" * 32 + sig[32:]  # non-canonical R (>= p)
        got = _assert_parity(
            [bytes(odd_pk), non_canon] + [k.pub_key().bytes()] * 2,
            [m] * 4,
            [sig, sig, bytes(odd_r), fat_r],
        )
        assert got[2] is False or got[2] == False  # odd R rejected
        assert not got[3]

    def test_empty_and_wrong_lengths(self):
        k = sr.PrivKeySr25519(b"\x0b" * 32)
        got = sr25519_batch.verify_batch(
            [b"short", k.pub_key().bytes()],
            [b"m", b"m"],
            [b"\x80" * 64, b"\x01" * 63],
        )
        assert got == [False, False]
        assert sr25519_batch.verify_batch([], [], []) == []


class TestThreeCurveBoundary:
    def test_all_three_kernels_in_one_batch(self):
        from cometbft_tpu.crypto import ed25519 as ed
        from cometbft_tpu.crypto import secp256k1 as secp
        from cometbft_tpu.crypto.batch import (
            TPUBatchVerifier,
            supports_batch_verification,
        )

        bv = TPUBatchVerifier(min_batch=1, slow_curve_min_batch=1, secp_min_batch=1)
        expect = []
        for i in range(2):
            k = ed.gen_priv_key_from_secret(bytes([i, 41]))
            m = b"ed %d" % i
            bv.add(k.pub_key(), m, k.sign(m))
            expect.append(True)
            assert supports_batch_verification(k.pub_key())
        for i in range(2):
            k = secp.gen_priv_key()
            m = b"secp %d" % i
            s = bytearray(k.sign(m))
            if i == 0:
                s[3] ^= 1
            bv.add(k.pub_key(), m, bytes(s))
            expect.append(
                secp.PubKeySecp256k1(k.pub_key().bytes()).verify_signature(
                    m, bytes(s)
                )
            )
            assert supports_batch_verification(k.pub_key())
        for i in range(2):
            k = sr.PrivKeySr25519(bytes([i + 1, 43] * 16))
            m = b"sr %d" % i
            sig = k.sign(m) if i else b"\x80" * 64
            bv.add(k.pub_key(), m, sig)
            expect.append(_cpu_verify(k.pub_key().bytes(), m, sig))
            assert supports_batch_verification(k.pub_key())
        ok, mask = bv.verify()
        assert mask == expect, (mask, expect)
