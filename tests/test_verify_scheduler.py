"""VerifyScheduler: cross-subsystem micro-batch coalescing.

Contract under test (crypto/scheduler.py):
  - concurrent submitters share ONE coalesced backend dispatch, with
    per-request verdict slices identical to serial verification;
  - a lone sub-floor request is released by the deadline flush within
    10x flush_us;
  - one caller's bad signature never fails another caller's request;
  - stop() drains — no future is left hanging;
  - a backend that dies mid-flight falls back to the CPU ground truth.
"""

import os
import threading
import time

import pytest

from cometbft_tpu.crypto import batch as cryptobatch
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.batch import (
    BackendSpec,
    CPUBatchVerifier,
    ScheduledBatchVerifier,
    new_batch_verifier,
    unwrap_backend,
)
from cometbft_tpu.crypto.scheduler import (
    DEFAULT_FLUSH_US,
    VerifyScheduler,
    flush_us_default,
)


def _make_items(n, tag=b"", poison_at=None):
    items = []
    for i in range(n):
        k = ed.gen_priv_key_from_secret(tag + bytes([i & 0xFF, i >> 8]))
        msg = b"scheduler-msg-" + tag + i.to_bytes(4, "big")
        sig = k.sign(msg)
        if poison_at is not None and i == poison_at:
            sig = b"\x00" * 64
        items.append((k.pub_key(), msg, sig))
    return items


def _serial_verdict(items):
    bv = CPUBatchVerifier()
    for pk, m, s in items:
        bv.add(pk, m, s)
    return bv.verify()


class CountingVerifier(CPUBatchVerifier):
    dispatches = 0
    sizes = []

    def verify(self):
        CountingVerifier.dispatches += 1
        CountingVerifier.sizes.append(self.count())
        return super().verify()


@pytest.fixture()
def counting_backend():
    CountingVerifier.dispatches = 0
    CountingVerifier.sizes = []
    cryptobatch.register_backend("counting", CountingVerifier)
    return BackendSpec("counting")


@pytest.fixture()
def sched(counting_backend):
    s = VerifyScheduler(spec=counting_backend, flush_us=5000)
    s.start()
    yield s
    if s.is_running():
        s.stop()


def _fanout(sched, reqs, timeout=60):
    results = [None] * len(reqs)
    barrier = threading.Barrier(len(reqs))

    def worker(i):
        barrier.wait()
        results[i] = sched.submit(reqs[i]).result(timeout=timeout)

    ts = [
        threading.Thread(target=worker, args=(i,)) for i in range(len(reqs))
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return results


class TestCoalescing:
    def test_concurrent_submitters_share_dispatches(self, sched):
        reqs = [_make_items(64, tag=bytes([i])) for i in range(4)]
        serial = [_serial_verdict(items) for items in reqs]
        results = _fanout(sched, reqs)
        # strictly fewer backend dispatches than submitters
        assert 1 <= CountingVerifier.dispatches < 4
        assert results == serial
        assert all(ok for ok, _ in results)
        assert sched.n_dispatches == CountingVerifier.dispatches

    def test_routing_sees_coalesced_size(self, sched):
        # each request is sub-floor; the backend must see the total
        reqs = [_make_items(8, tag=bytes([i])) for i in range(4)]
        _fanout(sched, reqs)
        assert max(CountingVerifier.sizes) > 8

    def test_poison_request_is_isolated(self, sched):
        reqs = [
            _make_items(16, tag=bytes([i]), poison_at=5 if i == 2 else None)
            for i in range(4)
        ]
        results = _fanout(sched, reqs)
        ok2, mask2 = results[2]
        assert not ok2
        assert mask2[5] is False or mask2[5] == False  # noqa: E712
        assert sum(1 for b in mask2 if not b) == 1
        for i in (0, 1, 3):
            ok, mask = results[i]
            assert ok and all(mask)

    def test_verdicts_match_serial_with_poison(self, sched):
        reqs = [
            _make_items(16, tag=bytes([i]), poison_at=i if i % 2 else None)
            for i in range(4)
        ]
        serial = [_serial_verdict(items) for items in reqs]
        assert _fanout(sched, reqs) == serial


class TestFlushTriggers:
    def test_deadline_flush_bounds_sub_floor_latency(self, sched):
        items = _make_items(3)
        t0 = time.perf_counter()
        ok, mask = sched.submit(items).result(timeout=60)
        dt = time.perf_counter() - t0
        assert ok and len(mask) == 3
        assert dt <= 10 * sched.flush_us / 1e6, (
            f"lone sub-floor request took {dt * 1e3:.1f}ms, "
            f"bound {10 * sched.flush_us / 1e3:.1f}ms"
        )

    def test_lane_budget_triggers_size_flush(self, counting_backend):
        s = VerifyScheduler(
            spec=counting_backend, flush_us=10_000_000, lane_budget=32
        )
        s.start()
        try:
            # deadline is 10s out; only the lane budget can release this
            fut = s.submit(_make_items(32))
            ok, mask = fut.result(timeout=5)
            assert ok and len(mask) == 32
        finally:
            s.stop()

    def test_explicit_flush_releases_early(self, counting_backend):
        s = VerifyScheduler(
            spec=counting_backend, flush_us=10_000_000, lane_budget=4096
        )
        s.start()
        try:
            fut = s.submit(_make_items(4))
            assert not fut.done()
            s.flush()
            ok, mask = fut.result(timeout=5)
            assert ok and len(mask) == 4
        finally:
            s.stop()

    def test_empty_submit_completes_immediately(self, sched):
        fut = sched.submit([])
        assert fut.done()
        assert fut.result(timeout=0) == (True, [])


class TestLifecycle:
    def test_stop_drains_pending_futures(self, counting_backend):
        # deadline far in the future: only the drain can release these
        s = VerifyScheduler(
            spec=counting_backend, flush_us=10_000_000, lane_budget=4096
        )
        s.start()
        futs = [s.submit(_make_items(8, tag=bytes([i]))) for i in range(3)]
        s.stop()
        for fut in futs:
            ok, mask = fut.result(timeout=5)
            assert ok and len(mask) == 8

    def test_submit_when_not_running_is_inline(self, counting_backend):
        s = VerifyScheduler(spec=counting_backend)
        fut = s.submit(_make_items(4))
        assert fut.done()  # complete before return — no one to wake it
        ok, mask = fut.result(timeout=0)
        assert ok and len(mask) == 4
        assert CountingVerifier.dispatches == 1

    def test_stop_is_idempotent_and_submit_survives(self, sched):
        sched.stop()
        fut = sched.submit(_make_items(2))
        assert fut.result(timeout=5)[0]


class TestFallback:
    def test_backend_death_mid_flight_falls_back_to_cpu(self):
        class ExplodingVerifier(CPUBatchVerifier):
            def verify(self):
                raise RuntimeError("device plane died")

        cryptobatch.register_backend("exploding", ExplodingVerifier)
        s = VerifyScheduler(spec=BackendSpec("exploding"), flush_us=2000)
        s.start()
        try:
            items = _make_items(8, poison_at=3)
            ok, mask = s.submit(items).result(timeout=30)
            # CPU ground truth still lands, bit-identical to serial
            assert (ok, mask) == _serial_verdict(items)
            assert s.metrics.cpu_fallbacks.value() == 1
        finally:
            s.stop()

    def test_short_mask_from_backend_falls_back(self):
        class TruncatingVerifier(CPUBatchVerifier):
            def verify(self):
                ok, mask = super().verify()
                return ok, mask[:-1]

        cryptobatch.register_backend("truncating", TruncatingVerifier)
        s = VerifyScheduler(spec=BackendSpec("truncating"), flush_us=2000)
        s.start()
        try:
            items = _make_items(4)
            ok, mask = s.submit(items).result(timeout=30)
            assert ok and len(mask) == 4
        finally:
            s.stop()


class TestBackendPlumbing:
    def test_new_batch_verifier_returns_adapter(self, sched):
        bv = new_batch_verifier(sched)
        assert isinstance(bv, ScheduledBatchVerifier)
        for pk, m, s in _make_items(5):
            bv.add(pk, m, s)
        assert bv.count() == 5
        ok, mask = bv.verify()
        assert ok and len(mask) == 5
        assert CountingVerifier.dispatches >= 1

    def test_unwrap_backend_yields_spec(self, sched):
        assert unwrap_backend(sched) is sched.spec
        assert cryptobatch.backend_name(sched) == "counting"
        spec = BackendSpec("cpu")
        assert unwrap_backend(spec) is spec

    def test_metrics_count_flush_reasons(self, sched):
        sched.submit(_make_items(2)).result(timeout=30)
        deadline = sched.metrics.flushes.with_labels(reason="deadline")
        explicit = sched.metrics.flushes.with_labels(reason="explicit")
        drain = sched.metrics.flushes.with_labels(reason="drain")
        total = deadline.value() + explicit.value() + drain.value()
        assert total >= 1
        assert sched.metrics.requests.value() == 1
        assert sched.metrics.signatures.value() == 2


class TestBlocksyncPipelined:
    """The blocksync rewire: window commits submitted as per-block
    scheduler requests, block i applying while i+1.. verify, with a
    bad commit only costing the suffix."""

    def _build(self, n_blocks, counting_backend):
        from cometbft_tpu.abci.client import LocalClient
        from cometbft_tpu.abci.kvstore import KVStoreApplication
        from cometbft_tpu.blocksync import BlocksyncReactor
        from cometbft_tpu.libs.db import MemDB
        from cometbft_tpu.proto.gogo import Timestamp
        from cometbft_tpu.proxy import AppConnConsensus
        from cometbft_tpu.state import make_genesis_state
        from cometbft_tpu.state.execution import BlockExecutor
        from cometbft_tpu.state.store import Store
        from cometbft_tpu.store import BlockStore
        from cometbft_tpu.types import test_util
        from cometbft_tpu.types.block import BlockID
        from cometbft_tpu.types.block import Commit
        from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

        chain_id = "sched-blocksync-chain"
        vals, privs = test_util.deterministic_validator_set(4, 10)
        doc = GenesisDoc(
            genesis_time=Timestamp(1_700_000_000, 0),
            chain_id=chain_id,
            validators=[
                GenesisValidator(v.address, v.pub_key, v.voting_power, "")
                for v in vals.validators
            ],
        )
        # build the source chain through the real executor
        state = make_genesis_state(doc)
        ss = Store(MemDB())
        ss.save(state)
        client = LocalClient(KVStoreApplication())
        client.start()
        executor = BlockExecutor(ss, AppConnConsensus(client))
        blocks = []
        last_commit = Commit(height=0, round=0)
        for h in range(1, n_blocks + 1):
            proposer = state.validators.validators[h % len(privs)].address
            block, parts = executor.create_proposal_block(
                h, state, last_commit, proposer
            )
            block_id = BlockID(block.hash(), parts.header())
            seen = test_util.make_commit(
                block_id, h, 0, state.validators, privs, chain_id,
                now=Timestamp(1_700_000_000 + h, 0),
            )
            blocks.append(block)
            state, _ = executor.apply_block(state, block_id, block)
            last_commit = seen

        # the fresh syncer, backed by the scheduler
        fresh = make_genesis_state(doc)
        fss = Store(MemDB())
        fss.save(fresh)
        fclient = LocalClient(KVStoreApplication())
        fclient.start()
        fexec = BlockExecutor(fss, AppConnConsensus(fclient))
        sched = VerifyScheduler(
            spec=counting_backend, flush_us=5000
        )
        sched.start()
        reactor = BlocksyncReactor(
            fresh, fexec, BlockStore(MemDB()), fast_sync=True,
            crypto_backend=sched,
        )

        class _FakePool:
            def __init__(self, blks):
                self.blocks = list(blks)
                self.height = 1

            def peek_window(self, n):
                return self.blocks[:n]

            def peek_two_blocks(self):
                first = self.blocks[0] if self.blocks else None
                second = self.blocks[1] if len(self.blocks) > 1 else None
                return first, second

            def pop_request(self):
                self.blocks.pop(0)
                self.height += 1

            def redo_request(self, h):
                return None

            def max_peer_height(self):
                return 0

        reactor.pool = _FakePool(blocks)
        return chain_id, fresh, reactor, sched, (client, fclient)

    def test_window_applies_through_scheduler(self, counting_backend):
        chain_id, state, reactor, sched, clients = self._build(
            6, counting_backend
        )
        try:
            new_state = reactor._try_sync_window(chain_id, state)
            # window of 6 blocks: the last one has no child commit yet,
            # so 5 apply — all through ONE coalesced dispatch
            assert new_state.last_block_height == 5
            assert sched.n_dispatches == 1
            assert reactor.blocks_synced == 5
        finally:
            sched.stop()
            for c in clients:
                c.stop()

    def test_bad_verdict_keeps_verified_prefix(self, counting_backend):
        chain_id, state, reactor, sched, clients = self._build(
            6, counting_backend
        )

        # corrupt the THIRD block's request at the submit boundary (the
        # commit embedded in the block can't be touched — the carrier
        # block's hash would change and the shape check would bail the
        # whole window, which is the pre-existing path): the pipelined
        # apply must keep the verified prefix and re-attribute from the
        # failure point via the single-block path
        class _PoisoningScheduler:
            def __init__(self, inner):
                self.inner = inner
                self.n = 0

            @property
            def spec(self):
                return self.inner.spec

            def submit(self, items, **kw):
                self.n += 1
                if self.n == 3:
                    items = [(pk, m, b"\x00" * 64) for pk, m, _ in items]
                return self.inner.submit(items, **kw)

        reactor.crypto_backend = _PoisoningScheduler(sched)
        try:
            new_state = reactor._try_sync_window(chain_id, state)
            # blocks 1-2 applied off their futures; height 3's bad
            # verdict stops the pipeline WITHOUT discarding them, and
            # the single-block fallback re-verifies the real commit
            # (which is valid — the poison was injected at submit) and
            # applies height 3 too
            assert new_state.last_block_height == 3
            assert reactor.blocks_synced == 3
        finally:
            sched.stop()
            for c in clients:
                c.stop()


class TestKnobs:
    def test_flush_us_precedence(self, monkeypatch):
        monkeypatch.delenv("CBFT_VERIFY_FLUSH_US", raising=False)
        assert flush_us_default() == DEFAULT_FLUSH_US
        assert flush_us_default(1234) == 1234
        monkeypatch.setenv("CBFT_VERIFY_FLUSH_US", "777")
        assert flush_us_default(1234) == 777

    def test_scheduler_reads_config_flush(self):
        s = VerifyScheduler(spec="cpu", flush_us=2500)
        assert s.flush_us == 2500
        assert s.spec.name == "cpu"


class TestSupervisedTriageIntegration:
    def test_coalesced_flush_triages_only_the_poisoned_request(self):
        # three coalesced requests from distinct subsystems, one carrying
        # a single bad signature: triage must (a) localize the failure to
        # that request's lanes and attribute it to its subsystem, (b)
        # complete the clean futures all_ok, (c) never move the breaker
        # (a bad signature is not a device incident)
        from cometbft_tpu.crypto.faults import FaultPlan, install
        from cometbft_tpu.crypto.supervisor import HEALTHY, BackendSupervisor

        name = "sched-triage-integration"
        install(name=name, inner="cpu", plan=FaultPlan(seed=99))
        sup = BackendSupervisor(
            spec=BackendSpec(name), dispatch_timeout_ms=2000,
            breaker_threshold=3, audit_pct=0,
            probe_base_ms=10, probe_max_ms=80, retry_ms=5,
        )
        sched = VerifyScheduler(spec=BackendSpec(name), flush_us=1000,
                                supervisor=sup)
        sched.start()
        try:
            good_a = _make_items(8, tag=b"cons")
            bad_b = _make_items(8, tag=b"bsync", poison_at=5)
            good_c = _make_items(8, tag=b"evid")
            futs = [
                sched.submit(good_a, subsystem="consensus", height=21),
                sched.submit(bad_b, subsystem="blocksync", height=22),
                sched.submit(good_c, subsystem="evidence", height=23),
            ]
            sched.flush()
            res = [f.result(timeout=30) for f in futs]

            ok_a, mask_a = res[0]
            ok_b, mask_b = res[1]
            ok_c, mask_c = res[2]
            assert ok_a and mask_a == [True] * 8
            assert ok_c and mask_c == [True] * 8
            assert not ok_b and mask_b == _serial_verdict(bad_b)[1]

            m = sup.metrics
            assert m.triage_runs.value() == 1
            offenders = {
                c._labels["subsystem"]: c.value()
                for c in m.triage_offenders._series()
                if "subsystem" in c._labels
            }
            assert offenders == {"blocksync": 1.0}
            assert m.triage_divergence.value() == 0
            assert sum(c.value() for c in m.trips._series()) == 0
            assert sup.state() == HEALTHY
        finally:
            sched.stop()
            sup.stop()
