"""WAL export/import CLI (wal2json/json2wal analogs).

Model: reference scripts/{wal2json,json2wal} — a real node's WAL exports
to JSON lines and re-imports to a byte-recoverable WAL that replay can
read.
"""

import json
import os

import pytest

from cometbft_tpu.cmd.commands import main as cli_main
from cometbft_tpu.consensus.wal import WAL, EndHeightMessage


@pytest.fixture()
def wal_file(tmp_path):
    """A WAL with real framed records (end-height markers at 1..3)."""
    path = str(tmp_path / "wal")
    wal = WAL(path)
    wal.start()
    try:
        for h in (1, 2, 3):
            wal.write_sync(EndHeightMessage(h))
    finally:
        wal.stop()
    return path


class TestWalTools:
    def test_export_emits_json_records(self, wal_file, capsys):
        assert cli_main(["wal", "export", wal_file]) == 0
        lines = [
            json.loads(ln)
            for ln in capsys.readouterr().out.strip().splitlines()
        ]
        heights = [
            r["height"] for r in lines if r["type"] == "EndHeightMessage"
        ]
        assert {1, 2, 3} <= set(heights)
        for r in lines:
            assert r["msg"]  # lossless hex body present
            assert r["time"]

    def test_roundtrip_produces_replayable_wal(
        self, wal_file, tmp_path, capsys
    ):
        assert cli_main(["wal", "export", wal_file]) == 0
        json_path = str(tmp_path / "wal.json")
        with open(json_path, "w") as f:
            f.write(capsys.readouterr().out)
        out_path = str(tmp_path / "wal.rebuilt")
        assert cli_main(["wal", "import", json_path, out_path]) == 0
        capsys.readouterr()

        # the rebuilt WAL decodes with the real WAL reader
        rebuilt = WAL(out_path)
        rebuilt.start()
        try:
            msgs = list(rebuilt.iter_messages())
        finally:
            rebuilt.stop()
        got = [
            m.height for m in msgs if isinstance(m, EndHeightMessage)
        ]
        assert {1, 2, 3} <= set(got)

    def test_import_rejects_garbage_records(self, tmp_path):
        json_path = str(tmp_path / "bad.json")
        with open(json_path, "w") as f:
            f.write(json.dumps({"time": None, "msg": "deadbeef"}) + "\n")
        with pytest.raises(Exception):
            cli_main(
                ["wal", "import", json_path, str(tmp_path / "out.wal")]
            )

    def test_export_stops_at_corruption(self, wal_file, capsys):
        with open(wal_file, "ab") as f:
            f.write(b"\xff" * 11)  # trailing garbage
        assert cli_main(["wal", "export", wal_file]) == 0
        err = capsys.readouterr().err
        assert "warning" in err


class TestRotatedGroupExport:
    def test_export_covers_rotated_chunks_oldest_first(
        self, tmp_path, capsys
    ):
        """Given the head path, export must emit the WHOLE rotated group
        in replay order — the head alone misses every record that
        rotated into .NNN chunks."""
        path = str(tmp_path / "wal")
        wal = WAL(path, group_head_size=100)
        wal.start()
        try:
            for h in range(1, 9):
                wal.write_sync(EndHeightMessage(h))
                wal.group().check_head_size_limit()
            assert len(wal.group().all_paths()) > 1, "never rotated"
        finally:
            wal.stop()
        assert cli_main(["wal", "export", path]) == 0
        lines = [
            json.loads(ln)
            for ln in capsys.readouterr().out.strip().splitlines()
        ]
        heights = [
            r["height"] for r in lines if r["type"] == "EndHeightMessage"
        ]
        assert heights == sorted(heights), "not in replay order"
        assert set(range(1, 9)) <= set(heights), heights
