"""Light-client verifying RPC proxy against a live node — honest
responses pass through verified; tampered responses are refused.

Model: reference light/proxy + light/rpc/client_test.go.
"""

import json
import tempfile
import time
import urllib.request

import pytest

from cometbft_tpu.cmd.commands import _load_config, main as cli_main
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.libs.net import free_ports
from cometbft_tpu.light.client import Client as LightClient, TrustOptions
from cometbft_tpu.light.provider import HTTPProvider
from cometbft_tpu.light.proxy import ErrProxyVerification, LightProxy
from cometbft_tpu.light.store import DBStore
from cometbft_tpu.node import default_new_node
from cometbft_tpu.rpc.client import HTTPClient


def _rpc(port, method, params):
    body = json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body,
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=10).read())


@pytest.mark.slow
class TestLightProxy:
    def test_verified_routes_and_tamper_rejection(self):
        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "proxy-chain"])
            rpc_port, p2p_port, proxy_port = free_ports(3)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            node = default_new_node(cfg)
            node.start()
            proxy = None
            try:
                client = HTTPClient(f"127.0.0.1:{rpc_port}")
                deadline = time.monotonic() + 60
                h = 0
                while time.monotonic() < deadline and h < 4:
                    try:
                        h = int(
                            client.status()["sync_info"]["latest_block_height"]
                        )
                    except Exception:
                        pass
                    time.sleep(0.3)
                assert h >= 4

                provider = HTTPProvider(
                    "proxy-chain", f"127.0.0.1:{rpc_port}"
                )
                lb1 = provider.light_block(1)
                lc = LightClient(
                    "proxy-chain",
                    TrustOptions(
                        period_ns=10**18,
                        height=1,
                        hash=lb1.signed_header.header.hash(),
                    ),
                    provider,
                    [HTTPProvider("proxy-chain", f"127.0.0.1:{rpc_port}")],
                    DBStore(MemDB()),
                )
                proxy = LightProxy(lc, client)
                proxy.serve("127.0.0.1", proxy_port)

                # verified block/commit/validators via the proxy's RPC
                blk = _rpc(proxy_port, "block", {"height": 2})["result"]
                assert int(blk["block"]["header"]["height"]) == 2
                cm = _rpc(proxy_port, "commit", {"height": 2})["result"]
                assert int(cm["signed_header"]["commit"]["height"]) == 2
                vals = _rpc(proxy_port, "validators", {"height": 2})["result"]
                assert len(vals["validators"]) == 1
                st = _rpc(proxy_port, "status", {})["result"]
                assert int(st["sync_info"]["latest_block_height"]) >= 4
                # unknown method → clean JSON-RPC error
                err = _rpc(proxy_port, "dump_consensus_state", {})
                assert err["error"]["code"] == -32601

                # a LYING primary: tamper the block response → refused
                real_block = client.block

                def lying_block(height=None):
                    res = real_block(height)
                    res["block"]["header"]["app_hash"] = "CC" * 32
                    return res

                client.block = lying_block
                resp = _rpc(proxy_port, "block", {"height": 3})
                assert "VERIFICATION FAILED" in resp["error"]["message"]
                client.block = real_block

                # a lying validators response → refused
                real_vals = client.validators

                def lying_vals(height=None, page=1, per_page=100):
                    res = real_vals(height, page=page, per_page=per_page)
                    res["validators"][0]["voting_power"] = "9999"
                    return res

                client.validators = lying_vals
                with pytest.raises(ErrProxyVerification):
                    proxy.validators(3)
            finally:
                if proxy is not None:
                    proxy.stop()
                node.stop()
