"""State layer: genesis, state store, block store, block executor.

Modeled on the reference's state package tests (state/state_test.go,
state/execution_test.go, store tests) — multi-height apply loop against
the kvstore app, validator-set persistence back-pointers, pruning.
"""

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import LocalClient
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.proxy import AppConnConsensus
from cometbft_tpu.state import State, StateVersion, make_genesis_state, median_time
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import ABCIResponses, Store
from cometbft_tpu.state.validation import validate_block
from cometbft_tpu.store import BlockStore
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.types import test_util
from cometbft_tpu.types.block import BlockID, Commit
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.event_bus import (
    EVENT_QUERY_NEW_BLOCK,
    EVENT_QUERY_TX,
    EventBus,
)


def _genesis_doc(n=4, power=10):
    vals, privs = test_util.deterministic_validator_set(n, power)
    gvs = [
        GenesisValidator(v.address, v.pub_key, v.voting_power, f"v{i}")
        for i, v in enumerate(vals.validators)
    ]
    doc = GenesisDoc(
        genesis_time=Timestamp(1_700_000_000, 0),
        chain_id="exec-chain",
        validators=gvs,
    )
    return doc, vals, privs


def _make_executor(event_bus=None):
    doc, vals, privs = _genesis_doc()
    state = make_genesis_state(doc)
    store = Store(MemDB())
    store.save(state)
    client = LocalClient(KVStoreApplication())
    client.start()
    executor = BlockExecutor(
        store, AppConnConsensus(client), event_bus=event_bus
    )
    return executor, state, privs, store


def _apply_n_blocks(executor, state, privs, n, txs_fn=None):
    last_commit = Commit(0, 0, BlockID(), [])
    for h in range(1, n + 1):
        proposer = state.validators.proposer.address
        block, parts = executor.create_proposal_block(
            h, state, last_commit, proposer
        )
        if txs_fn:
            from cometbft_tpu.types.tx import Txs

            block.data.txs = Txs(txs_fn(h))
            block.header.data_hash = b""
            block.fill_header()
        # recompute hash after any data change
        block._hash = None
        block_id = BlockID(block.hash(), parts.header())
        state, _ = executor.apply_block(state, block_id, block)
        last_commit = test_util.make_commit(
            block_id, h, 0, state.last_validators, privs, state.chain_id
        )
    return state


class TestGenesis:
    def test_roundtrip_json(self):
        doc, _, _ = _genesis_doc()
        raw = doc.to_json()
        doc2 = GenesisDoc.from_json(raw)
        assert doc2.chain_id == doc.chain_id
        assert doc2.initial_height == 1
        assert len(doc2.validators) == 4
        assert doc2.validator_hash() == doc.validator_hash()

    def test_validate_rejects_bad(self):
        doc = GenesisDoc(chain_id="")
        assert "chain_id" in doc.validate_and_complete()
        doc = GenesisDoc(chain_id="x" * 51)
        assert "too long" in doc.validate_and_complete()
        doc, _, _ = _genesis_doc()
        doc.validators[0].power = 0
        assert "voting power" in doc.validate_and_complete()

    def test_genesis_state(self):
        doc, vals, _ = _genesis_doc()
        st = make_genesis_state(doc)
        assert st.last_block_height == 0
        assert st.validators.hash() == vals.hash()
        assert st.next_validators is not st.validators
        assert st.initial_height == 1


class TestStateStore:
    def test_save_load_roundtrip(self):
        doc, _, _ = _genesis_doc()
        st = make_genesis_state(doc)
        store = Store(MemDB())
        store.save(st)
        st2 = store.load()
        assert st2.equals(st)

    def test_validator_back_pointers(self):
        executor, state, privs, store = _make_executor()
        state = _apply_n_blocks(executor, state, privs, 5)
        # validators were never changed: every stored height resolves
        for h in range(1, 7):
            vs = store.load_validators(h)
            assert vs.size() == 4
        from cometbft_tpu.state.store import ErrNoValSetForHeight

        with pytest.raises(ErrNoValSetForHeight):
            store.load_validators(100)

    def test_consensus_params_info(self):
        doc, _, _ = _genesis_doc()
        st = make_genesis_state(doc)
        store = Store(MemDB())
        store.save(st)
        params = store.load_consensus_params(1)
        assert params.block.max_bytes == st.consensus_params.block.max_bytes


class TestBlockStore:
    def test_save_load_prune(self):
        from cometbft_tpu.types.part_set import PartSet, BLOCK_PART_SIZE_BYTES

        doc, vals, privs = _genesis_doc()
        st = make_genesis_state(doc)
        bs = BlockStore(MemDB())
        assert bs.height() == 0 and bs.base() == 0

        last_commit = Commit(0, 0, BlockID(), [])
        blocks = []
        for h in range(1, 5):
            block, parts = st.make_block(
                h, [b"tx-%d" % h], last_commit, [], st.validators.proposer.address
            )
            block_id = BlockID(block.hash(), parts.header())
            seen = test_util.make_commit(
                block_id, h, 0, st.validators, privs, st.chain_id
            )
            bs.save_block(block, parts, seen)
            blocks.append((block, block_id))
            # advance minimal state bits used by make_block
            st.last_block_height = h
            st.last_block_id = block_id
            last_commit = seen

        assert bs.height() == 4 and bs.base() == 1 and bs.size() == 4
        b2 = bs.load_block(2)
        assert b2.hash() == blocks[1][0].hash()
        assert bs.load_block_by_hash(b2.hash()).header.height == 2
        meta = bs.load_block_meta(3)
        assert meta.block_id == blocks[2][1]
        assert bs.load_seen_commit(4).height == 4
        assert bs.load_block_commit(3).height == 3  # saved from block 4's LastCommit

        pruned = bs.prune_blocks(3)
        assert pruned == 2
        assert bs.base() == 3
        assert bs.load_block(2) is None
        assert bs.load_block(3) is not None

    def test_non_contiguous_save_rejected(self):
        doc, _, privs = _genesis_doc()
        st = make_genesis_state(doc)
        bs = BlockStore(MemDB())
        block, parts = st.make_block(
            1, [], Commit(0, 0, BlockID(), []), [], st.validators.proposer.address
        )
        bid = BlockID(block.hash(), parts.header())
        seen = test_util.make_commit(bid, 1, 0, st.validators, privs, st.chain_id)
        bs.save_block(block, parts, seen)
        block3, parts3 = st.make_block(
            3, [], Commit(0, 0, BlockID(), []), [], st.validators.proposer.address
        )
        with pytest.raises(ValueError, match="contiguous"):
            bs.save_block(block3, parts3, seen)


class TestBlockExecutor:
    def test_apply_five_blocks(self):
        executor, state, privs, store = _make_executor()
        state = _apply_n_blocks(
            executor, state, privs, 5, txs_fn=lambda h: [b"k%d=v%d" % (h, h)]
        )
        assert state.last_block_height == 5
        # kvstore app hash is the 8-byte varint of tx count
        assert len(state.app_hash) == 8
        reloaded = store.load()
        assert reloaded.equals(state)
        responses = store.load_abci_responses(3)
        assert len(responses.deliver_txs) == 1
        assert responses.deliver_txs[0].is_ok()

    def test_validate_block_rejects_tampering(self):
        executor, state, privs, store = _make_executor()
        state = _apply_n_blocks(executor, state, privs, 1)
        proposer = state.validators.proposer.address
        last_commit_bad = Commit(0, 0, BlockID(), [])
        with pytest.raises(ValueError):
            # wrong height commit for h=2 (needs real last commit)
            block, parts = executor.create_proposal_block(
                2, state, last_commit_bad, proposer
            )
            validate_block(state, block)

    def test_wrong_app_hash_rejected(self):
        executor, state, privs, store = _make_executor()
        state = _apply_n_blocks(executor, state, privs, 2)
        bad = state.copy()
        bad.app_hash = b"\x01" * 8
        proposer = state.validators.proposer.address
        # build block against the real state, validate against tampered
        last_commit = test_util.make_commit(
            state.last_block_id, 2, 0, state.last_validators, privs, state.chain_id
        )
        block, parts = executor.create_proposal_block(
            3, state, last_commit, proposer
        )
        with pytest.raises(ValueError, match="AppHash"):
            validate_block(bad, block)

    def test_events_fired(self):
        bus = EventBus()
        bus.start()
        sub_block = bus.subscribe("test", EVENT_QUERY_NEW_BLOCK)
        sub_tx = bus.subscribe("test2", EVENT_QUERY_TX)
        executor, state, privs, store = _make_executor(event_bus=bus)
        state = _apply_n_blocks(
            executor, state, privs, 1, txs_fn=lambda h: [b"a=b"]
        )
        msg = sub_block.next(timeout=2)
        assert msg.data.block.header.height == 1
        txmsg = sub_tx.next(timeout=2)
        assert txmsg.data.tx == b"a=b"
        assert "tx.hash" in txmsg.events
        bus.stop()


class TestMedianTime:
    def test_weighted_median(self):
        vals, privs = test_util.deterministic_validator_set(3, 10)
        bid = test_util.make_block_id()
        t0 = Timestamp(100, 0)
        commit = test_util.make_commit(bid, 5, 0, vals, privs, "c", now=t0)
        # all timestamps equal → median equals it
        assert median_time(commit, vals) == t0
