"""Device SHA-512 + mod-L scalar pipeline — parity with hashlib/CPython.

These are the pieces CBFT_TPU_HASH=device fuses in front of the Straus
loop (crypto/tpu/{sha512,scalar}.py). Parity must be exact: sc_reduce
feeds cofactorless verification, where h and h + kL differ on torsioned
keys. Runs on the virtual CPU platform (conftest.py).
"""

import hashlib

import numpy as np

from cometbft_tpu.crypto.tpu import scalar, sha512


class TestSha512Kernel:
    def test_ragged_parity_with_hashlib(self):
        msgs = [
            b"",
            b"abc",
            b"x" * 111,  # 1-block boundary: 111 + 1 + 16 = 128
            b"y" * 112,  # first length that needs 2 blocks
            b"z" * 127,
            b"w" * 128,
            b"q" * 200,
            bytes(range(256)) * 2,
        ]
        hi, lo, nb = sha512.pad_ragged_np(msgs)
        dh, dl = sha512.sha512_blocks(hi, lo, nb)
        got = sha512.digests_to_bytes_np(np.asarray(dh), np.asarray(dl))
        for i, m in enumerate(msgs):
            assert got[i].tobytes() == hashlib.sha512(m).digest(), i

    def test_random_lengths(self):
        rng = np.random.default_rng(23)
        msgs = [rng.bytes(int(rng.integers(0, 400))) for _ in range(32)]
        hi, lo, nb = sha512.pad_ragged_np(msgs)
        dh, dl = sha512.sha512_blocks(hi, lo, nb)
        got = sha512.digests_to_bytes_np(np.asarray(dh), np.asarray(dl))
        for i, m in enumerate(msgs):
            assert got[i].tobytes() == hashlib.sha512(m).digest(), i


class TestScReduce:
    def _reduce_ints(self, vals):
        import jax.numpy as jnp

        cols = [
            jnp.array([(v >> (15 * k)) & 0x7FFF for v in vals], jnp.int32)
            for k in range(35)
        ]
        red = np.asarray(scalar.sc_reduce(cols))
        return [
            sum(int(red[j, i]) << (15 * j) for j in range(17))
            for i in range(len(vals))
        ]

    def test_edge_values(self):
        L = scalar.L
        vals = [0, 1, L - 1, L, L + 1, 8 * L, 2**512 - 1, 2**255,
                2**256 - 1, (L << 260) + 12345, 7 * L - 3]
        got = self._reduce_ints(vals)
        assert got == [v % L for v in vals]

    def test_digest_pipeline_matches_python(self):
        rng = np.random.default_rng(31)
        msgs = [rng.bytes(int(rng.integers(0, 300))) for _ in range(24)]
        hi, lo, nb = sha512.pad_ragged_np(msgs)
        dh, dl = sha512.sha512_blocks(hi, lo, nb)
        red = np.asarray(scalar.sc_reduce(scalar.digest_to_limbs(dh, dl)))
        for i, m in enumerate(msgs):
            want = int.from_bytes(hashlib.sha512(m).digest(), "little") % scalar.L
            got = sum(int(red[j, i]) << (15 * j) for j in range(17))
            assert got == want, i

    def test_digit_extraction_matches_host_oracle(self):
        rng = np.random.default_rng(37)
        msgs = [rng.bytes(40) for _ in range(16)]
        hi, lo, nb = sha512.pad_ragged_np(msgs)
        dh, dl = sha512.sha512_blocks(hi, lo, nb)
        red = scalar.sc_reduce(scalar.digest_to_limbs(dh, dl))
        got = np.asarray(scalar.digits_msb_first(red))
        arr = np.zeros((len(msgs), 32), np.uint8)
        for i, m in enumerate(msgs):
            h = int.from_bytes(hashlib.sha512(m).digest(), "little") % scalar.L
            arr[i] = np.frombuffer(h.to_bytes(32, "little"), np.uint8)
        # independent numpy oracle: 2-bit LE digit pairs, MSB first
        bits = np.unpackbits(arr, axis=-1, bitorder="little")
        digits = bits[:, 0:254:2] + 2 * bits[:, 1:254:2]
        want = np.ascontiguousarray(digits[:, ::-1].astype(np.int32).T)
        assert (got == want).all()
