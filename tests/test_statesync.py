"""Statesync: message codec, snapshot pool, chunk queue, syncer against an
in-proc snapshot app, and a full restore-then-blocksync over real TCP.

Model: reference statesync/{messages,snapshots,chunks,syncer,reactor}_test.go
plus the node handoff in node/node.go:651-706 (state sync → fast sync →
consensus).
"""

import threading
import time

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import LocalClient
from cometbft_tpu.abci.kvstore import SnapshotKVStoreApplication
from cometbft_tpu.config import test_config as make_test_config
from cometbft_tpu.consensus.reactor import ConsensusReactor
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import NilWAL
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.blocksync import BLOCKSYNC_CHANNEL, BlocksyncReactor
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.light.client import TrustOptions
from cometbft_tpu.light.provider import BlockStoreProvider
from cometbft_tpu.p2p import (
    MultiplexTransport,
    NetAddress,
    NodeInfo,
    NodeKey,
    ProtocolVersion,
    Switch,
)
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.proxy import AppConnConsensus, AppConnQuery, AppConnSnapshot
from cometbft_tpu.state import StateVersion, make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.statesync import (
    CHUNK_CHANNEL,
    SNAPSHOT_CHANNEL,
    Chunk,
    ChunkQueue,
    ChunkRequest,
    ChunkResponse,
    ErrChunkQueueDone,
    ErrRejectSnapshot,
    LightClientStateProvider,
    Snapshot,
    SnapshotPool,
    SnapshotsRequest,
    SnapshotsResponse,
    StateSyncReactor,
    Syncer,
    decode_statesync_message,
    encode_statesync_message,
)
from cometbft_tpu.statesync import syncer as syncer_mod
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types import test_util
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

CHAIN_ID = "statesync-test-chain"
GENESIS_TIME = Timestamp(1_700_000_000, 0)


class TestStatesyncCodec:
    def test_all_messages_roundtrip(self):
        msgs = [
            SnapshotsRequest(),
            SnapshotsResponse(10, 1, 3, b"h" * 32, b"meta"),
            ChunkRequest(10, 1, 2),
            ChunkResponse(10, 1, 2, b"body", False),
        ]
        for m in msgs:
            dec = decode_statesync_message(encode_statesync_message(m))
            assert type(dec) is type(m)
        dec = decode_statesync_message(
            encode_statesync_message(SnapshotsResponse(10, 1, 3, b"h" * 32, b"m"))
        )
        assert (dec.height, dec.format, dec.chunks) == (10, 1, 3)

    def test_validation_rules(self):
        # snapshot without hash / chunk both-missing-and-body (messages.go)
        with pytest.raises(ValueError):
            decode_statesync_message(
                encode_statesync_message(SnapshotsResponse(10, 1, 3, b"", b""))
            )
        with pytest.raises(ValueError):
            decode_statesync_message(
                encode_statesync_message(ChunkResponse(10, 1, 2, b"x", True))
            )
        with pytest.raises(Exception):
            decode_statesync_message(b"")


class TestSnapshotPool:
    def _snap(self, height=10, format=1, chunks=2, tag=b"a"):
        return Snapshot(height, format, chunks, tag * 32, b"")

    def test_ranked_prefers_height_then_format_then_peers(self):
        pool = SnapshotPool()
        s_low = self._snap(height=5)
        s_high = self._snap(height=20)
        s_fmt2 = Snapshot(20, 2, 2, b"b" * 32, b"")
        pool.add("p1", s_low)
        pool.add("p1", s_high)
        pool.add("p2", s_high)
        pool.add("p1", s_fmt2)
        ranked = pool.ranked()
        assert ranked[0].format == 2  # same height, greater format wins
        assert ranked[1].height == 20
        assert ranked[-1].height == 5
        assert pool.best().format == 2

    def test_reject_and_blacklists(self):
        pool = SnapshotPool()
        s = self._snap()
        pool.add("p1", s)
        pool.reject(s)
        assert pool.best() is None
        assert not pool.add("p1", s)  # blacklisted forever

        s2 = Snapshot(11, 7, 2, b"c" * 32, b"")
        pool.add("p1", s2)
        pool.reject_format(7)
        assert pool.best() is None
        assert not pool.add("p1", Snapshot(12, 7, 2, b"d" * 32, b""))

        pool.reject_peer("p9")
        assert not pool.add("p9", self._snap(tag=b"e"))

    def test_remove_peer_drops_orphaned_snapshots(self):
        pool = SnapshotPool()
        s = self._snap()
        pool.add("p1", s)
        pool.add("p2", s)
        pool.remove_peer("p1")
        assert pool.best() is not None
        pool.remove_peer("p2")
        assert pool.best() is None

    def test_get_peers_sorted(self):
        pool = SnapshotPool()
        s = self._snap()
        pool.add("pB", s)
        pool.add("pA", s)
        assert pool.get_peers(s) == ["pA", "pB"]


class TestChunkQueue:
    def _queue(self, chunks=3):
        return ChunkQueue(Snapshot(10, 1, chunks, b"h" * 32, b""))

    def test_in_order_iteration(self):
        q = self._queue()
        try:
            # arrive out of order; next() returns 0,1,2
            for i in (2, 0, 1):
                assert q.add(Chunk(10, 1, i, bytes([i + 1]) * 4, f"p{i}"))
            got = [q.next(1.0).index for _ in range(3)]
            assert got == [0, 1, 2]
            with pytest.raises(ErrChunkQueueDone):
                q.next(0.1)
        finally:
            q.close()

    def test_duplicate_and_invalid_chunks(self):
        q = self._queue()
        try:
            assert q.add(Chunk(10, 1, 0, b"x", "p"))
            assert not q.add(Chunk(10, 1, 0, b"y", "p"))  # duplicate
            with pytest.raises(ValueError):
                q.add(Chunk(11, 1, 0, b"x", "p"))  # wrong height
            with pytest.raises(ValueError):
                q.add(Chunk(10, 1, 99, b"x", "p"))  # out of range
        finally:
            q.close()

    def test_allocate_retry_discard(self):
        q = self._queue()
        try:
            assert sorted(q.allocate() for _ in range(3)) == [0, 1, 2]
            with pytest.raises(ErrChunkQueueDone):
                q.allocate()
            q.add(Chunk(10, 1, 0, b"x", "pA"))
            assert q.next(1.0).index == 0
            q.retry(0)
            assert q.next(1.0).index == 0  # returned again
            q.discard(0)
            assert not q.has(0)
            # discarded chunk is allocatable again
            assert q.allocate() == 0
        finally:
            q.close()

    def test_discard_sender_only_unreturned(self):
        q = self._queue()
        try:
            q.add(Chunk(10, 1, 0, b"x", "bad"))
            q.add(Chunk(10, 1, 1, b"y", "bad"))
            assert q.next(1.0).index == 0  # chunk 0 returned
            q.discard_sender("bad")
            assert q.has(0)  # already returned: kept
            assert not q.has(1)  # unreturned from bad sender: dropped
        finally:
            q.close()

    def test_blocking_next_wakes_on_add(self):
        q = self._queue(chunks=1)
        try:
            got = []
            t = threading.Thread(
                target=lambda: got.append(q.next(5.0).index), daemon=True
            )
            t.start()
            time.sleep(0.1)
            q.add(Chunk(10, 1, 0, b"x", "p"))
            t.join(2.0)
            assert got == [0]
        finally:
            q.close()


# -- syncer against an in-proc snapshot app ----------------------------------


class _StaticStateProvider:
    """Hands out pre-built trusted data (reference: statesync/mocks)."""

    def __init__(self, state, commit, app_hash_):
        self._state = state
        self._commit = commit
        self._app_hash = app_hash_

    def app_hash(self, height):
        return self._app_hash

    def commit(self, height):
        return self._commit

    def state(self, height):
        return self._state


def _make_doc(n_vals=4):
    vals, privs = test_util.deterministic_validator_set(n_vals, 10)
    doc = GenesisDoc(
        genesis_time=GENESIS_TIME,
        chain_id=CHAIN_ID,
        validators=[
            GenesisValidator(v.address, v.pub_key, v.voting_power, "")
            for v in vals.validators
        ],
    )
    return doc, vals, privs


def _build_chain(doc, privs, n_blocks, snapshot_interval, chunk_size=200):
    """Build a chain through the real executor with a snapshotting app."""
    from cometbft_tpu.types.block import BlockID, Commit

    state = make_genesis_state(doc)
    # the ABCI handshake stamps the app's protocol version into the state
    # (consensus/replay.go:263-265); headers then carry it
    state.version.consensus_app = 1
    state_store = Store(MemDB())
    state_store.save(state)
    block_store = BlockStore(MemDB())
    app = SnapshotKVStoreApplication(
        snapshot_interval=snapshot_interval, chunk_size=chunk_size
    )
    client = LocalClient(app)
    client.start()
    executor = BlockExecutor(state_store, AppConnConsensus(client))

    last_commit = Commit(height=0, round=0)
    for h in range(1, n_blocks + 1):
        proposer = state.validators.validators[h % len(privs)].address
        # a tx per block so snapshots carry real kv state
        txs = [f"key{h}=value{h}".encode()]
        block, parts = state.make_block(h, txs, last_commit, [], proposer)
        block_id = BlockID(block.hash(), parts.header())
        seen_commit = test_util.make_commit(
            block_id, h, 0, state.validators, privs, doc.chain_id,
            now=Timestamp(GENESIS_TIME.seconds + h, 0),
        )
        block_store.save_block(block, parts, seen_commit)
        state, _ = executor.apply_block(state, block_id, block)
        last_commit = seen_commit
    return state, state_store, block_store, client, app


class TestSyncer:
    def test_restores_snapshot_into_fresh_app(self):
        doc, vals, privs = _make_doc()
        state, ss, bs, client, src_app = _build_chain(
            doc, privs, 12, snapshot_interval=10
        )
        snap_meta = src_app._snapshots[-1]
        assert snap_meta.height == 10
        assert snap_meta.chunks > 1  # multi-chunk snapshot

        # fresh app + syncer; chunks served straight from the source app
        fresh_app = SnapshotKVStoreApplication()
        fresh_client = LocalClient(fresh_app)
        fresh_client.start()

        trusted_state = ss.load_validators(10)  # sanity: exists
        assert trusted_state is not None
        header11 = bs.load_block_meta(11).header
        commit10 = bs.load_block_commit(10)

        provider_state = make_genesis_state(doc)
        provider_state.last_block_height = 10
        provider_state.app_hash = header11.app_hash
        provider_state.version = StateVersion(consensus_app=1)

        sp = _StaticStateProvider(provider_state, commit10, header11.app_hash)

        requested = []

        def send_chunk_request(peer_id, snapshot, index):
            requested.append(index)
            resp = client.load_snapshot_chunk_sync(
                abci.RequestLoadSnapshotChunk(
                    height=snapshot.height, format=1, chunk=index
                )
            )
            syncer.add_chunk(
                Chunk(snapshot.height, 1, index, resp.chunk, peer_id)
            )

        syncer = Syncer(
            sp,
            AppConnSnapshot(fresh_client),
            AppConnQuery(fresh_client),
            chunk_fetchers=2,
            retry_timeout=1.0,
            send_chunk_request=send_chunk_request,
        )
        syncer.add_snapshot(
            "peer1",
            Snapshot(
                height=snap_meta.height,
                format=snap_meta.format,
                chunks=snap_meta.chunks,
                hash=snap_meta.hash,
            ),
        )
        new_state, commit, used = syncer.sync_any(0)
        assert new_state.last_block_height == 10
        assert commit.height == 10
        # app restored: Info reports snapshot height and hash
        info = fresh_app.info(abci.RequestInfo())
        assert info.last_block_height == 10
        assert info.last_block_app_hash == header11.app_hash
        # kv pairs made it across
        q = fresh_app.query(abci.RequestQuery(data=b"key5", path="/store"))
        assert q.value == b"value5"
        client.stop()
        fresh_client.stop()

    def test_stop_aborts_discovery_loop(self):
        """Node shutdown must terminate a sync_any that found no snapshots."""
        fresh_client = LocalClient(SnapshotKVStoreApplication())
        fresh_client.start()
        syncer = Syncer(
            _StaticStateProvider(None, None, b""),
            AppConnSnapshot(fresh_client),
            AppConnQuery(fresh_client),
        )
        result = {}

        def run():
            try:
                syncer.sync_any(0.5)
            except Exception as exc:
                result["err"] = exc

        t = threading.Thread(target=run, daemon=True)
        t.start()
        time.sleep(0.3)
        syncer.stop()
        t.join(5.0)
        assert not t.is_alive()
        assert isinstance(result["err"], syncer_mod.ErrAbort)
        fresh_client.stop()

    def test_rejects_snapshot_on_bad_app_hash(self):
        doc, vals, privs = _make_doc()
        state, ss, bs, client, src_app = _build_chain(
            doc, privs, 12, snapshot_interval=10
        )
        snap_meta = src_app._snapshots[-1]
        fresh_app = SnapshotKVStoreApplication()
        fresh_client = LocalClient(fresh_app)
        fresh_client.start()

        commit10 = bs.load_block_commit(10)
        provider_state = make_genesis_state(doc)
        provider_state.version = StateVersion(consensus_app=1)
        sp = _StaticStateProvider(provider_state, commit10, b"\xde\xad" * 16)

        def send_chunk_request(peer_id, snapshot, index):
            resp = client.load_snapshot_chunk_sync(
                abci.RequestLoadSnapshotChunk(
                    height=snapshot.height, format=1, chunk=index
                )
            )
            syncer.add_chunk(
                Chunk(snapshot.height, 1, index, resp.chunk, peer_id)
            )

        syncer = Syncer(
            sp,
            AppConnSnapshot(fresh_client),
            AppConnQuery(fresh_client),
            chunk_fetchers=1,
            retry_timeout=1.0,
            chunk_timeout=10.0,
            send_chunk_request=send_chunk_request,
        )
        snap = Snapshot(
            snap_meta.height, 1, snap_meta.chunks, snap_meta.hash
        )
        syncer.add_snapshot("peer1", snap)
        chunks = ChunkQueue(snap)
        with pytest.raises(syncer_mod.ErrVerifyFailed):
            # wrong trusted app hash → restore completes but verify_app fails
            syncer.sync(snap, chunks)
        chunks.close()
        client.stop()
        fresh_client.stop()


# -- full TCP statesync → blocksync handoff -----------------------------------


class _SSNode:
    """A node with statesync + blocksync + consensus reactors over TCP."""

    def __init__(self, doc, state, state_store, block_store, client,
                 fast_sync):
        self.state_store = state_store
        self.block_store = block_store
        self.client = client
        executor = BlockExecutor(state_store, AppConnConsensus(client))
        self.executor = executor
        cfg = make_test_config()
        cfg.consensus.wal_path = ""
        self.cons = ConsensusState(
            cfg.consensus, state, executor, block_store, wal=NilWAL()
        )
        self.cons_reactor = ConsensusReactor(self.cons, wait_sync=True)
        self.bs_reactor = BlocksyncReactor(
            state, executor, block_store, fast_sync=fast_sync
        )
        self.ss_reactor = StateSyncReactor(
            cfg.statesync,
            AppConnSnapshot(client),
            AppConnQuery(client),
        )
        self.node_key = NodeKey(ed.gen_priv_key())
        info = NodeInfo(
            protocol_version=ProtocolVersion(),
            node_id=self.node_key.id(),
            listen_addr="127.0.0.1:0",
            network=doc.chain_id,
            channels=bytes(
                [SNAPSHOT_CHANNEL, CHUNK_CHANNEL, BLOCKSYNC_CHANNEL,
                 0x20, 0x21, 0x22, 0x23]
            ),
            moniker="ss-test",
        )
        self.transport = MultiplexTransport(info, self.node_key)
        self.transport.listen(NetAddress("", "127.0.0.1", 0))
        info.listen_addr = f"127.0.0.1:{self.transport.listen_addr.port}"
        self.switch = Switch(self.transport, reconnect_interval=0.2)
        self.switch.add_reactor("STATESYNC", self.ss_reactor)
        self.switch.add_reactor("BLOCKSYNC", self.bs_reactor)
        self.switch.add_reactor("CONSENSUS", self.cons_reactor)

    def start(self):
        self.switch.start()

    def stop(self):
        for svc in (self.switch, self.client):
            try:
                if svc.is_running():
                    svc.stop()
            except Exception:
                pass


@pytest.mark.slow
class TestStateSyncFromConfig:
    def test_fresh_node_statesyncs_via_rpc_servers(self, monkeypatch):
        """The reference boot path end to end: a fresh node with
        [statesync] enable + rpc_servers + trust root restores a snapshot
        discovered over p2p, verified via HTTP light providers, then
        blocksyncs and switches to consensus (node.go:651-706)."""
        import tempfile

        from cometbft_tpu.cmd.commands import _load_config, main as cli_main
        from cometbft_tpu.node import default_new_node
        from cometbft_tpu.rpc.client import HTTPClient
        from cometbft_tpu.statesync import syncer as syncer_mod_

        monkeypatch.setattr(syncer_mod_, "MINIMUM_DISCOVERY_TIME", 0.5)

        from cometbft_tpu.libs.net import free_ports

        with tempfile.TemporaryDirectory() as d:
            # source: a single-validator chain with a snapshotting app
            src_home = f"{d}/src"
            cli_main(["--home", src_home, "init", "--chain-id", "ss-cfg"])
            src_rpc, src_p2p, fresh_rpc, fresh_p2p = free_ports(4)
            cfg = _load_config(src_home)
            cfg.base.proxy_app = "snapshot_kvstore"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{src_rpc}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{src_p2p}"
            cfg.consensus.timeout_commit_ns = 100_000_000  # fast blocks
            source = default_new_node(cfg)
            source.start()
            try:
                client = HTTPClient(f"127.0.0.1:{src_rpc}")
                deadline = time.monotonic() + 120
                height = 0
                # wait for a snapshot (taken at height 10) + light blocks
                # through height 13
                while time.monotonic() < deadline and height < 13:
                    try:
                        height = int(
                            client.status()["sync_info"]["latest_block_height"]
                        )
                    except Exception:
                        pass
                    time.sleep(0.3)
                assert height >= 13, f"source stuck at {height}"

                # fresh node: same genesis, statesync from config
                fresh_home = f"{d}/fresh"
                import os
                import shutil

                cli_main(["--home", fresh_home, "init", "--chain-id", "x"])
                shutil.copy(
                    f"{src_home}/config/genesis.json",
                    f"{fresh_home}/config/genesis.json",
                )
                fcfg = _load_config(fresh_home)
                fcfg.base.proxy_app = "snapshot_kvstore"
                fcfg.rpc.laddr = f"tcp://127.0.0.1:{fresh_rpc}"
                fcfg.p2p.laddr = f"tcp://127.0.0.1:{fresh_p2p}"
                src_id = source.node_key.id()
                fcfg.p2p.persistent_peers = (
                    f"{src_id}@127.0.0.1:{src_p2p}"
                )
                fcfg.statesync.enable = True
                fcfg.statesync.rpc_servers = [
                    f"127.0.0.1:{src_rpc}",
                    f"127.0.0.1:{src_rpc}",
                ]
                fcfg.statesync.trust_height = 1
                block1 = client.block(1)
                fcfg.statesync.trust_hash = block1["block_id"]["hash"]
                fcfg.statesync.discovery_time_ns = 500_000_000
                fresh = default_new_node(fcfg)
                fresh.start()
                try:
                    fclient = HTTPClient(f"127.0.0.1:{fresh_rpc}")
                    deadline = time.monotonic() + 120
                    fheight = 0
                    while time.monotonic() < deadline and fheight < 11:
                        try:
                            fheight = int(
                                fclient.status()["sync_info"][
                                    "latest_block_height"
                                ]
                            )
                        except Exception:
                            pass
                        time.sleep(0.5)
                    # restored from the height-10 snapshot and kept going
                    assert fheight >= 11, (
                        f"fresh node reached only {fheight}"
                    )
                    assert fresh.state_store.load_validators(11) is not None
                finally:
                    fresh.stop()
            finally:
                source.stop()


@pytest.mark.slow
class TestStateSyncOverTCP:
    def test_fresh_node_statesyncs_then_blocksyncs(self, monkeypatch):
        monkeypatch.setattr(syncer_mod, "MINIMUM_DISCOVERY_TIME", 0.3)
        doc, vals, privs = _make_doc()
        n_blocks = 30
        state, ss, bs, client, src_app = _build_chain(
            doc, privs, n_blocks, snapshot_interval=10, chunk_size=150
        )
        server = _SSNode(doc, state, ss, bs, client, fast_sync=False)

        fresh_state = make_genesis_state(doc)
        fss = Store(MemDB())
        fss.save(fresh_state)
        fresh_client = LocalClient(SnapshotKVStoreApplication())
        fresh_client.start()
        fbs = BlockStore(MemDB())
        fresh = _SSNode(
            doc, fresh_state, fss, fbs, fresh_client, fast_sync=False
        )
        server.start()
        fresh.start()
        try:
            fresh.switch.dial_peer_with_address(server.transport.listen_addr)
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and not fresh.switch.peers.size():
                time.sleep(0.05)
            assert fresh.switch.peers.size() > 0

            # trusted root: header at height 1 from the source chain
            trust_hash = bs.load_block_meta(1).block_id.hash
            provider_a = BlockStoreProvider(doc.chain_id, bs, ss)
            provider_b = BlockStoreProvider(doc.chain_id, bs, ss)
            sp = LightClientStateProvider(
                doc.chain_id,
                StateVersion(consensus_app=1),
                doc.initial_height,
                [provider_a, provider_b],
                TrustOptions(
                    period_ns=10**18, height=1, hash=trust_hash
                ),
            )
            new_state, commit = fresh.ss_reactor.sync(sp, 0.3)
            # best snapshot is height 30, but the source chain has no
            # header at 31/32 yet → rejected; 20 restores
            assert new_state.last_block_height == 20
            fss.bootstrap(new_state)
            fbs.save_seen_commit(20, commit)

            # handoff: blocksync from 21 to the tip
            fresh.bs_reactor.switch_to_fast_sync(new_state)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if fresh.block_store.height() >= n_blocks - 1:
                    break
                time.sleep(0.2)
            assert fresh.block_store.height() >= n_blocks - 1, (
                f"blocksync reached only {fresh.block_store.height()}"
            )
            # the restored app + blocksynced blocks agree with the source
            for h in (21, 25, n_blocks - 1):
                want = bs.load_block_meta(h).block_id.hash
                got = fresh.block_store.load_block_meta(h).block_id.hash
                assert want == got
        finally:
            fresh.stop()
            server.stop()
