"""TPU Merkle kernel: bit-identical parity with the recursive CPU tree.

Model: reference crypto/merkle/tree_test.go (known-shape roots) plus the
CPU/TPU golden-parity discipline used for the ed25519 kernel.
"""

import hashlib

import numpy as np
import pytest

from cometbft_tpu.crypto import merkle as cpu_merkle
from cometbft_tpu.crypto.tpu import merkle as tpu_merkle
from cometbft_tpu.crypto.tpu import sha256 as tpu_sha


class TestJaxSha256:
    @pytest.mark.parametrize("msg_len", [0, 1, 32, 55, 56, 64, 65, 100, 119])
    def test_matches_hashlib(self, msg_len):
        rng = np.random.default_rng(msg_len)
        msgs = rng.integers(0, 256, (8, msg_len), dtype=np.uint8)
        blocks = tpu_sha.pad_messages_np(msgs, msg_len)
        digests = tpu_sha.digests_to_bytes_np(
            np.asarray(tpu_sha.sha256_blocks(blocks))
        )
        for i in range(8):
            want = hashlib.sha256(msgs[i].tobytes()).digest()
            assert digests[i].tobytes() == want, f"len={msg_len} row={i}"


class TestMerkleParity:
    def _leaves(self, n, seed=7):
        rng = np.random.default_rng(seed)
        # variable-length leaves like SimpleValidator encodings
        return [rng.bytes(int(rng.integers(1, 90))) for _ in range(n)]

    @pytest.mark.parametrize("n", list(range(0, 40)) + [63, 64, 65, 127, 128, 129, 400])
    def test_root_parity_all_shapes(self, n):
        leaves = self._leaves(n)
        want = cpu_merkle.hash_from_byte_slices(leaves)
        got = tpu_merkle.hash_from_byte_slices(leaves, force_device=True)
        assert got == want, f"n={n}"

    def test_mega_set_parity(self):
        """10k-leaf root (the mega-commit ValidatorSet.Hash case)."""
        leaves = self._leaves(10_000, seed=11)
        want = cpu_merkle.hash_from_byte_slices(leaves)
        got = tpu_merkle.hash_from_byte_slices(leaves, force_device=True)
        assert got == want

    def test_enable_parallel_routes_large_calls(self):
        leaves = self._leaves(300, seed=3)
        want = cpu_merkle.hash_from_byte_slices(leaves)
        cpu_merkle.enable_parallel(True)
        try:
            got = cpu_merkle.hash_from_byte_slices(leaves)
        finally:
            cpu_merkle.enable_parallel(False)
        assert got == want

    def test_validator_set_hash_parity(self):
        from cometbft_tpu.types import test_util

        vals, _ = test_util.deterministic_validator_set(150, 10)
        want = vals.hash()
        cpu_merkle.enable_parallel(True)
        try:
            got = vals.hash()
        finally:
            cpu_merkle.enable_parallel(False)
        assert got == want


class TestPallasSha256:
    """The Pallas kernel (CBFT_TPU_SHA=pallas) must match hashlib and the
    XLA path bit for bit. Interpret mode runs the kernel eagerly (a few
    seconds per distinct shape), so the parity matrix below — single
    block, the 65-byte merkle inner-node shape, a multi-tile batch, and a
    multi-block message — is marked slow; real-hardware runs go through
    CBFT_TPU_SHA=pallas against the merkle suite."""

    @pytest.mark.slow
    def test_interpret_mode_parity(self):
        import hashlib

        import numpy as np

        from cometbft_tpu.crypto.tpu import sha256 as tpu_sha
        from cometbft_tpu.crypto.tpu import sha256_pallas

        rng = np.random.default_rng(11)
        # one block, the merkle inner-node shape (2 blocks), a multi-tile
        # batch (grid > 1), and a longer multi-block message
        for n, msg_len in ((3, 55), (5, 65), (130, 65), (4, 200)):
            msgs = rng.integers(0, 256, size=(n, msg_len), dtype=np.uint8)
            blocks = tpu_sha.pad_messages_np(msgs, msg_len)
            got = np.asarray(
                sha256_pallas.sha256_blocks(blocks, interpret=True)
            )
            got_bytes = tpu_sha.digests_to_bytes_np(got)
            for i in range(n):
                want = hashlib.sha256(msgs[i].tobytes()).digest()
                assert got_bytes[i].tobytes() == want, f"n={n} i={i}"

    def test_env_dispatch(self, monkeypatch):
        import numpy as np

        from cometbft_tpu.crypto.tpu import sha256 as tpu_sha

        msgs = np.zeros((4, 65), np.uint8)
        blocks = tpu_sha.pad_messages_np(msgs, 65)
        want = np.asarray(tpu_sha.sha256_blocks(blocks))
        monkeypatch.setenv("CBFT_TPU_SHA", "nonsense")
        import pytest as _pytest

        with _pytest.raises(ValueError):
            tpu_sha.sha256_blocks(blocks)
        monkeypatch.delenv("CBFT_TPU_SHA")
        assert (np.asarray(tpu_sha.sha256_blocks(blocks)) == want).all()
