"""Consensus over real TCP: N validator nodes, each with its own switch,
transport, and consensus reactor, gossiping blocks/votes over
SecretConnection + MConnection — no direct callbacks.

Model: reference consensus/reactor_test.go (startConsensusNet) — commits
with all validators, with one down (3/4 > 2/3), and catch-up of a lagging
node via gossip-data/votes catch-up from the block store.
"""

import time

import pytest

from cometbft_tpu.abci.client import LocalClient
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import test_config as make_test_config
from cometbft_tpu.consensus.reactor import (
    DATA_CHANNEL,
    STATE_CHANNEL,
    VOTE_CHANNEL,
    VOTE_SET_BITS_CHANNEL,
    ConsensusReactor,
)
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import NilWAL
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.p2p import (
    MultiplexTransport,
    NetAddress,
    NodeInfo,
    NodeKey,
    ProtocolVersion,
    Switch,
)
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.proxy import AppConnConsensus
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types import test_util
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

from cometbft_tpu.evidence.reactor import EVIDENCE_CHANNEL
from cometbft_tpu.mempool.reactor import MEMPOOL_CHANNEL

CHANNELS = bytes(
    [
        STATE_CHANNEL,
        DATA_CHANNEL,
        VOTE_CHANNEL,
        VOTE_SET_BITS_CHANNEL,
        MEMPOOL_CHANNEL,
        EVIDENCE_CHANNEL,
    ]
)


class Node:
    def __init__(self, doc: GenesisDoc, priv_val):
        from cometbft_tpu.evidence.pool import Pool as EvidencePool
        from cometbft_tpu.evidence.reactor import EvidenceReactor
        from cometbft_tpu.mempool.clist_mempool import CListMempool
        from cometbft_tpu.mempool.reactor import MempoolReactor
        from cometbft_tpu.proxy import AppConnMempool
        from cometbft_tpu.state.execution import BlockExecutor

        state = make_genesis_state(doc)
        self.state_store = Store(MemDB())
        self.state_store.save(state)
        self.block_store = BlockStore(MemDB())
        self.client = LocalClient(KVStoreApplication())
        self.client.start()

        test_cfg = make_test_config()
        self.mempool = CListMempool(
            test_cfg.mempool, AppConnMempool(self.client)
        )
        self.evpool = EvidencePool(
            MemDB(), self.state_store, self.block_store
        )
        executor = BlockExecutor(
            self.state_store,
            AppConnConsensus(self.client),
            mempool=self.mempool,
            evidence_pool=self.evpool,
        )
        cfg = test_cfg.consensus
        cfg.wal_path = ""
        self.cons = ConsensusState(
            cfg, state, executor, self.block_store, evpool=self.evpool,
            wal=NilWAL(),
        )
        self.cons.set_priv_validator(priv_val)
        self.reactor = ConsensusReactor(self.cons)
        self.mempool_reactor = MempoolReactor(test_cfg.mempool, self.mempool)
        self.evidence_reactor = EvidenceReactor(self.evpool)

        self.node_key = NodeKey(ed.gen_priv_key())
        info = NodeInfo(
            protocol_version=ProtocolVersion(),
            node_id=self.node_key.id(),
            listen_addr="127.0.0.1:0",
            network=doc.chain_id,
            channels=CHANNELS,
            moniker="cons-test",
        )
        self.transport = MultiplexTransport(info, self.node_key)
        self.transport.listen(NetAddress("", "127.0.0.1", 0))
        info.listen_addr = (
            f"127.0.0.1:{self.transport.listen_addr.port}"
        )
        self.switch = Switch(self.transport, reconnect_interval=0.2)
        self.switch.add_reactor("MEMPOOL", self.mempool_reactor)
        self.switch.add_reactor("EVIDENCE", self.evidence_reactor)
        self.switch.add_reactor("CONSENSUS", self.reactor)

    def start(self):
        self.switch.start()

    def stop(self):
        for svc in (self.switch, self.client):
            try:
                if svc.is_running():
                    svc.stop()
            except Exception:
                pass

    def addr(self) -> NetAddress:
        return self.transport.listen_addr

    def height(self) -> int:
        return self.cons.height()


def _make_net(n=4):
    vals, privs = test_util.deterministic_validator_set(n, 10)
    doc = GenesisDoc(
        genesis_time=Timestamp(1_700_000_000, 0),
        chain_id="reactor-test-chain",
        validators=[
            GenesisValidator(v.address, v.pub_key, v.voting_power, "")
            for v in vals.validators
        ],
    )
    return [Node(doc, privs[i]) for i in range(n)], doc, privs


def _connect_all(nodes, timeout=60.0):
    """Dial until a full mesh forms, re-dialing failed pairs.

    Simultaneous cross-dials can reject one direction as a duplicate while
    the other also dies (close races) — on a single-core box with no retry
    the mesh never completes, so retry with surfaced errors instead of
    fire-and-forget (reference: p2p/switch.go reconnectToPeer persistence).
    """
    want = len(nodes) - 1
    deadline = time.monotonic() + timeout
    errs: list = []
    while time.monotonic() < deadline:
        if all(
            _dial_from(a, nodes, errs) >= want for a in nodes
        ):
            return
        time.sleep(0.25)
    raise AssertionError(
        f"mesh incomplete after {timeout}s; peers="
        f"{[n.switch.peers.size() for n in nodes]}; "
        f"last dial error: {errs[-1] if errs else None!r}"
    )


def _dial_from(node, peers, errs: list = None) -> int:
    """Dial every not-yet-connected peer once; return current peer count."""
    for p in peers:
        if p is node or node.switch.peers.has(p.node_key.id()):
            continue
        try:
            node.switch.dial_peer_with_address(p.addr())
        except Exception as exc:
            if errs is not None:
                errs.append(exc)
    return node.switch.peers.size()


def _wait(cond, timeout=60.0, interval=0.05, desc=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc or 'condition'}")


@pytest.mark.slow
class TestConsensusOverTCP:
    def test_four_validators_commit_over_tcp(self):
        nodes, _, _ = _make_net(4)
        for n in nodes:
            n.start()
        try:
            _connect_all(nodes)
            _wait(
                lambda: all(n.switch.peers.size() == 3 for n in nodes),
                desc="full mesh",
            )
            _wait(
                lambda: all(n.height() > 3 for n in nodes),
                timeout=90,
                desc="height 3 on all nodes",
            )
            # every node committed identical blocks
            for h in (1, 2, 3):
                hashes = {
                    n.block_store.load_block_meta(h).block_id.hash
                    for n in nodes
                }
                assert len(hashes) == 1, f"height {h} diverged"
        finally:
            for n in nodes:
                n.stop()

    def test_commits_with_one_node_down(self):
        nodes, _, _ = _make_net(4)
        for n in nodes[:3]:  # node 3 never starts
            n.start()
        try:
            _connect_all(nodes[:3])
            _wait(
                lambda: all(n.switch.peers.size() == 2 for n in nodes[:3]),
                desc="3-node mesh",
            )
            _wait(
                lambda: all(n.height() > 2 for n in nodes[:3]),
                timeout=90,
                desc="progress with 3/4 validators",
            )
        finally:
            for n in nodes:
                n.stop()

    def test_tx_gossips_and_commits_across_the_net(self):
        """Reference: the full tx lifecycle (SURVEY §3.3) — a tx submitted
        to one node travels mempool gossip (0x30), is reaped by whichever
        node proposes, committed, and the app state is updated on every
        node."""
        nodes, _, _ = _make_net(4)
        for n in nodes:
            n.start()
        try:
            _connect_all(nodes)
            _wait(
                lambda: all(n.height() > 1 for n in nodes),
                timeout=60,
                desc="initial progress",
            )
            # submit to node 0 only
            nodes[0].mempool.check_tx(b"k1=v1", None)
            # every other node's mempool sees it via gossip (unless it was
            # already committed out from under the mempool)
            def tx_committed(n):
                from cometbft_tpu.abci import types as abci

                res = n.client.query_sync(
                    abci.RequestQuery(path="/store", data=b"k1")
                )
                return res.value == b"v1"

            _wait(
                lambda: all(tx_committed(n) for n in nodes),
                timeout=90,
                desc="tx committed and readable on all nodes",
            )
            # the tx is inside one committed block, identical everywhere
            heights_with_tx = [
                h
                for h in range(1, nodes[0].height() + 1)
                if nodes[0].block_store.load_block(h) is not None
                and b"k1=v1" in list(nodes[0].block_store.load_block(h).data.txs)
            ]
            assert len(heights_with_tx) == 1, heights_with_tx
            h = heights_with_tx[0]
            for n in nodes[1:]:
                blk = n.block_store.load_block(h)
                assert blk is not None and b"k1=v1" in list(blk.data.txs)
            # mempools drained
            _wait(
                lambda: all(n.mempool.size() == 0 for n in nodes),
                timeout=30,
                desc="mempools drained",
            )
        finally:
            for n in nodes:
                n.stop()

    def test_evidence_gossips_and_lands_in_a_block(self):
        """Duplicate-vote evidence added on one node is gossiped (0x38),
        included in a proposal, validated by every node's pool, and marked
        committed everywhere (reference: evidence/reactor.go +
        state/execution.go CreateProposalBlock evidence inclusion)."""
        from cometbft_tpu.proto.gogo import Timestamp
        from cometbft_tpu.types import test_util
        from cometbft_tpu.types.evidence import DuplicateVoteEvidence
        from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PRECOMMIT

        nodes, doc, privs = _make_net(4)
        for n in nodes:
            n.start()
        try:
            _connect_all(nodes)
            _wait(
                lambda: all(n.height() > 2 for n in nodes),
                timeout=60,
                desc="initial progress",
            )
            # craft equivocation by validator 0 at height 1, timestamped
            # with block 1's committed time so every pool verifies it
            block_time = nodes[0].block_store.load_block_meta(1).header.time
            vals = nodes[0].cons.state.last_validators
            pv = privs[0]
            idx, _ = vals.get_by_address(pv.get_pub_key().address())
            v1 = test_util.make_vote(
                pv, doc.chain_id, idx, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT,
                test_util.make_block_id(b"\xaa" * 32), timestamp=block_time,
            )
            v2 = test_util.make_vote(
                pv, doc.chain_id, idx, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT,
                test_util.make_block_id(b"\xbb" * 32), timestamp=block_time,
            )
            ev = DuplicateVoteEvidence.new(
                v1, v2, block_time, nodes[0].cons.state.validators
            )
            nodes[1].evpool.add_evidence(ev)

            def ev_in_committed_block(n):
                for h in range(2, n.height() + 1):
                    blk = n.block_store.load_block(h)
                    if blk is not None and any(
                        e.hash() == ev.hash() for e in blk.evidence
                    ):
                        return True
                return False

            _wait(
                lambda: all(ev_in_committed_block(n) for n in nodes),
                timeout=90,
                desc="evidence committed on all nodes",
            )
            # pools marked it committed: pending everywhere drains
            _wait(
                lambda: all(n.evpool.size() == 0 for n in nodes),
                timeout=30,
                desc="evidence pools drained",
            )
        finally:
            for n in nodes:
                n.stop()

    def test_lagging_node_catches_up_via_gossip(self):
        nodes, _, _ = _make_net(4)
        # start only 3; they can commit (3/4 power > 2/3)
        for n in nodes[:3]:
            n.start()
        try:
            _connect_all(nodes[:3])
            _wait(
                lambda: all(n.height() > 4 for n in nodes[:3]),
                timeout=90,
                desc="initial progress",
            )
            # node 3 joins late at genesis height: it must catch up
            # exclusively via consensus gossip (block parts from the store
            # + catchup commits)
            nodes[3].start()
            _wait(
                lambda: _dial_from(nodes[3], nodes[:3]) >= 1,
                timeout=30,
                interval=0.25,
                desc="late node connecting to at least one peer",
            )
            target = max(n.height() for n in nodes[:3])
            _wait(
                lambda: nodes[3].height() >= target,
                timeout=120,
                desc=f"late node catching up to {target}",
            )
            # catch-up blocks match the ones the others committed
            for h in range(1, target - 1):
                want = nodes[0].block_store.load_block_meta(h).block_id.hash
                got = nodes[3].block_store.load_block_meta(h)
                assert got is not None, f"late node missing block {h}"
                assert got.block_id.hash == want
        finally:
            for n in nodes:
                n.stop()


@pytest.mark.slow
class TestMaverickDoubleSigner:
    def test_live_equivocation_is_detected_and_committed(self):
        """Maverick analog (test/maverick double-prevote/precommit): node 0
        broadcasts a CONFLICTING precommit for the very vote it just cast.
        Honest nodes' HeightVoteSets detect the conflict, route it through
        report_conflicting_votes into their evidence pools, and the
        DuplicateVoteEvidence ends up inside a committed block everywhere
        (consensus/state.go tryAddVote ErrVoteConflictingVotes +
        evidence/pool.go processConsensusBuffer)."""
        import threading as _threading

        from cometbft_tpu.consensus.messages import (
            VoteMessage,
            encode_consensus_message,
        )
        from cometbft_tpu.consensus.reactor import VOTE_CHANNEL
        from cometbft_tpu.types.block import BlockID, PartSetHeader
        from cometbft_tpu.types.evidence import DuplicateVoteEvidence
        from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PRECOMMIT, Vote

        nodes, doc, privs = _make_net(4)
        maverick = nodes[0]
        pv = privs[0]

        # wrap _sign_add_vote: BEFORE casting the genuine precommit, gossip
        # a conflicting one for the same H/R — peers then hold both votes
        # within the live round, exactly like the reference maverick's
        # double-precommit misbehavior
        genuine_sign = maverick.cons._sign_add_vote
        equivocated = _threading.Event()

        def double_sign(msg_type, hash_, header):
            rs = maverick.cons.rs
            if (
                msg_type == SIGNED_MSG_TYPE_PRECOMMIT
                and rs.height >= 2
                and hash_  # only equivocate on real (non-nil) precommits
                and not equivocated.is_set()
                and maverick.cons.priv_validator_pub_key is not None
            ):
                idx, _ = rs.validators.get_by_address(
                    maverick.cons.priv_validator_pub_key.address()
                )
                conflict = Vote(
                    type=msg_type,
                    height=rs.height,
                    round=rs.round,
                    block_id=BlockID(
                        b"\xee" * 32, PartSetHeader(1, b"\xdd" * 32)
                    ),
                    timestamp=Timestamp(1_700_000_000, 0),
                    validator_address=(
                        maverick.cons.priv_validator_pub_key.address()
                    ),
                    validator_index=idx,
                )
                pv.sign_vote(doc.chain_id, conflict)
                maverick.switch.broadcast(
                    VOTE_CHANNEL,
                    encode_consensus_message(VoteMessage(conflict)),
                )
                genuine = genuine_sign(msg_type, hash_, header)
                if genuine is not None:
                    # push the genuine vote directly too so both votes hit
                    # every peer back-to-back within the live round (the
                    # normal gossip path can lose the race against commit)
                    maverick.switch.broadcast(
                        VOTE_CHANNEL,
                        encode_consensus_message(VoteMessage(genuine)),
                    )
                    equivocated.set()
                return genuine
            return genuine_sign(msg_type, hash_, header)

        maverick.cons._sign_add_vote = double_sign

        for n in nodes:
            n.start()
        try:
            _connect_all(nodes)
            _wait(
                lambda: equivocated.is_set(),
                timeout=90,
                desc="maverick equivocating",
            )

            def evidence_committed(n):
                for h in range(2, n.height() + 1):
                    blk = n.block_store.load_block(h)
                    if blk is None:
                        continue
                    for ev in blk.evidence:
                        if isinstance(ev, DuplicateVoteEvidence) and (
                            ev.vote_a.validator_address
                            == pv.get_pub_key().address()
                        ):
                            return True
                return False

            # at least 3 honest nodes commit the equivocation evidence
            _wait(
                lambda: sum(
                    1 for n in nodes[1:] if evidence_committed(n)
                ) >= 3,
                timeout=120,
                desc="evidence committed on honest nodes",
            )
        finally:
            for n in nodes:
                n.stop()
