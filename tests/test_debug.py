"""Debug/profiling tooling: pprof-analog endpoints, debug dump bundle,
and the read-only inspect server over a crashed home.

Model: reference node/node.go:896 (pprof server) +
cmd/cometbft/commands/debug/{dump,inspect}.go.
"""

import base64
import json
import subprocess
import sys
import tarfile
import tempfile
import time
import urllib.request

import pytest

from cometbft_tpu.cmd.commands import _load_config, main as cli_main
from cometbft_tpu.libs.debug import PprofServer, thread_stacks
from cometbft_tpu.libs.net import free_ports


class TestPprof:
    def test_thread_stacks_include_current_thread(self):
        dump = thread_stacks()
        assert "MainThread" in dump
        assert "test_thread_stacks_include_current_thread" in dump

    def test_server_routes(self):
        srv = PprofServer()
        port = srv.serve("127.0.0.1", 0)
        try:
            stacks = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/stacks", timeout=5
            ).read().decode()
            assert "MainThread" in stacks
            gc_out = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/gc", timeout=5
            ).read().decode()
            assert "objects tracked" in gc_out
            heap = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/heap", timeout=5
            ).read().decode()
            assert "tracemalloc" in heap
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=5
                )
        finally:
            srv.stop()


def _rpc_post(port, method, params):
    body = json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body,
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=10).read())


@pytest.mark.slow
class TestDebugCLI:
    def test_dump_and_inspect_on_real_home(self):
        """Run a node with pprof enabled, dump a bundle while it is live,
        stop it ('crash'), then inspect the dead home."""
        from cometbft_tpu.node import default_new_node

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "debug-chain"])
            rpc_port, p2p_port, pprof_port, inspect_port = free_ports(4)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.base.db_backend = "sqlite"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            cfg.rpc.pprof_laddr = f"tcp://127.0.0.1:{pprof_port}"
            # persist the overridden ports so `debug dump` reads them
            from cometbft_tpu.config import write_config_file
            import os

            write_config_file(os.path.join(d, "config", "config.toml"), cfg)
            node = default_new_node(cfg)
            node.start()
            try:
                deadline = time.monotonic() + 60
                committed = None
                while time.monotonic() < deadline and committed is None:
                    try:
                        committed = _rpc_post(
                            rpc_port, "broadcast_tx_commit",
                            {"tx": base64.b64encode(b"dbg=1").decode()},
                        )["result"]
                    except Exception:
                        time.sleep(0.3)
                assert committed is not None

                # pprof endpoint live on the node
                stacks = urllib.request.urlopen(
                    f"http://127.0.0.1:{pprof_port}/debug/stacks", timeout=5
                ).read().decode()
                assert "consensus" in stacks or "receive" in stacks

                bundle = os.path.join(d, "bundle.tar.gz")
                assert cli_main(
                    ["--home", d, "debug", "dump", "--output", bundle]
                ) == 0
                with tarfile.open(bundle) as tar:
                    names = tar.getnames()
                    assert "status.json" in names
                    assert "config.toml" in names
                    assert "stacks.txt" in names
                    status = json.loads(
                        tar.extractfile("status.json").read()
                    )
                    assert "result" in status
            finally:
                node.stop()
            time.sleep(0.5)

            # inspect the dead home from a separate process
            import cometbft_tpu

            repo_root = os.path.dirname(
                os.path.dirname(os.path.abspath(cometbft_tpu.__file__))
            )
            env = dict(os.environ, PYTHONPATH=repo_root)
            proc = subprocess.Popen(
                [sys.executable, "-m", "cometbft_tpu", "--home", d,
                 "debug", "inspect",
                 "--laddr", f"tcp://127.0.0.1:{inspect_port}"],
                env=env,
            )
            try:
                deadline = time.monotonic() + 30
                status = None
                while time.monotonic() < deadline and status is None:
                    try:
                        status = json.loads(urllib.request.urlopen(
                            f"http://127.0.0.1:{inspect_port}/status",
                            timeout=3,
                        ).read())
                    except Exception:
                        time.sleep(0.3)
                assert status is not None and status["height"] >= 1
                blk = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{inspect_port}/block?height=1",
                    timeout=5,
                ).read())
                assert int(blk["block"]["header"]["height"]) == 1
                vals = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{inspect_port}/validators?height=1",
                    timeout=5,
                ).read())
                assert len(vals["validators"]) == 1
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{inspect_port}/block?height=99999",
                        timeout=5,
                    )
                    raise AssertionError("missing block served")
                except urllib.error.HTTPError as e:
                    assert "error" in json.loads(e.read())
            finally:
                proc.kill()
                proc.wait()


class TestDebugKill:
    def test_kill_bundles_then_signals(self, tmp_path):
        """`debug kill --pid N`: the bundle lands BEFORE the SIGABRT
        (reference debug/kill.go order — the node is about to die)."""
        import signal
        import subprocess
        import sys as _sys
        import tarfile

        from cometbft_tpu.cmd.commands import main as cli_main

        home = str(tmp_path / "home")
        cli_main(["--home", home, "init", "--chain-id", "dbg-kill"])
        victim = subprocess.Popen(
            [_sys.executable, "-c", "import time; time.sleep(60)"]
        )
        out = str(tmp_path / "bundle.tar.gz")
        try:
            rc = cli_main(
                ["--home", home, "debug", "kill",
                 "--pid", str(victim.pid), "--output", out]
            )
            assert rc == 0
            assert tarfile.is_tarfile(out)
            assert victim.wait(timeout=10) == -signal.SIGABRT
        finally:
            if victim.poll() is None:
                victim.kill()
        # missing pid is a usage error, not a signal to pid 0
        assert cli_main(["--home", home, "debug", "kill"]) == 1
