"""Node assembly + CLI + RPC: init/testnet commands, single-node boot with
RPC smoke, and a 4-process kvstore localnet committing a tx end to end.

Model: reference node/node_test.go (NewNode/OnStart), rpc tests, and the
networks/local docker-compose localnet driven by `cometbft testnet`.
"""

import base64
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

import pytest

from cometbft_tpu.cmd.commands import main as cli_main
from cometbft_tpu.libs.net import free_ports as _free_ports


def _rpc(port, route, timeout=5):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/{route}", timeout=timeout
    ) as r:
        return json.load(r)


def _rpc_post(port, method, params, timeout=30):
    body = json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.load(r)


class TestCLI:
    def test_init_creates_node_home(self):
        with tempfile.TemporaryDirectory() as d:
            assert cli_main(["--home", d, "init", "--chain-id", "cli-test"]) == 0
            for f in (
                "config/genesis.json",
                "config/config.toml",
                "config/node_key.json",
                "config/priv_validator_key.json",
                "data/priv_validator_state.json",
            ):
                assert os.path.exists(os.path.join(d, f)), f
            with open(os.path.join(d, "config/genesis.json")) as fh:
                doc = json.load(fh)
            assert doc["chain_id"] == "cli-test"
            assert len(doc["validators"]) == 1
            # idempotent
            assert cli_main(["--home", d, "init"]) == 0

    def test_testnet_creates_wired_homes(self):
        with tempfile.TemporaryDirectory() as d:
            out = os.path.join(d, "net")
            assert (
                cli_main(
                    ["testnet", "--v", "3", "--output-dir", out,
                     "--chain-id", "net-test"]
                )
                == 0
            )
            genesis = []
            for i in range(3):
                with open(os.path.join(out, f"node{i}", "config/genesis.json")) as fh:
                    genesis.append(fh.read())
                with open(os.path.join(out, f"node{i}", "config/config.toml")) as fh:
                    toml = fh.read()
                assert "persistent_peers" in toml
            # same genesis everywhere, 3 validators in it
            assert len(set(genesis)) == 1
            assert len(json.loads(genesis[0])["validators"]) == 3

    def test_testnet_hostname_template_for_containers(self):
        """--hostname-template writes 0.0.0.0 binds + hostname peers (the
        docker-compose/k8s network shape)."""
        import tempfile

        from cometbft_tpu.cmd.commands import _load_config

        with tempfile.TemporaryDirectory() as d:
            cli_main([
                "testnet", "--v", "3", "--output-dir", d,
                "--chain-id", "compose-chain",
                "--hostname-template", "node{}",
            ])
            for i in range(3):
                cfg = _load_config(os.path.join(d, f"node{i}"))
                assert cfg.p2p.laddr == "tcp://0.0.0.0:26656"
                assert cfg.rpc.laddr == "tcp://0.0.0.0:26657"
                peers = cfg.p2p.persistent_peers.split(",")
                assert len(peers) == 2
                for p_ in peers:
                    host_port = p_.split("@")[1]
                    assert host_port.endswith(":26656")
                    assert host_port.startswith("node")
                    assert f"node{i}:" not in p_  # never dials itself

    def test_show_node_id_and_validator(self, capsys):
        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init"])
            capsys.readouterr()
            assert cli_main(["--home", d, "show-node-id"]) == 0
            node_id = capsys.readouterr().out.strip()
            assert len(node_id) == 40  # hex address
            assert cli_main(["--home", d, "show-validator"]) == 0
            pk = json.loads(capsys.readouterr().out)
            assert pk["type"] == "tendermint/PubKeyEd25519"


class TestSingleNode:
    def test_boot_commit_rpc(self):
        """default_new_node boots from an init'ed home, commits blocks,
        serves RPC, accepts a tx through broadcast_tx_commit."""
        from cometbft_tpu.cmd.commands import _load_config
        from cometbft_tpu.node import default_new_node

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "single-node"])
            rpc_port, p2p_port = _free_ports(2)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            (prom_port,) = _free_ports(1)
            cfg.instrumentation.prometheus = True
            cfg.instrumentation.prometheus_listen_addr = (
                f"127.0.0.1:{prom_port}"
            )
            node = default_new_node(cfg)
            node.start()
            try:
                deadline = time.monotonic() + 60
                height = 0
                while time.monotonic() < deadline:
                    try:
                        st = _rpc(rpc_port, "status")["result"]
                        height = int(st["sync_info"]["latest_block_height"])
                        if height >= 2:
                            break
                    except Exception:
                        pass
                    time.sleep(0.3)
                assert height >= 2, "single node never committed"
                tx = base64.b64encode(b"one=1").decode()
                res = _rpc_post(port=rpc_port, method="broadcast_tx_commit",
                                params={"tx": tx})["result"]
                assert res["deliver_tx"]["code"] == 0
                q = _rpc(
                    rpc_port,
                    "abci_query?path=/store&data=0x" + b"one".hex(),
                )["result"]["response"]
                assert base64.b64decode(q["value"]) == b"1"

                # the indexer service picked the tx up: /tx by hash and
                # /tx_search by height both find it
                import hashlib

                tx_hash = hashlib.sha256(b"one=1").digest()
                tx_height = int(res["height"])
                deadline = time.monotonic() + 10
                got = None
                while time.monotonic() < deadline and got is None:
                    try:
                        got = _rpc_post(
                            port=rpc_port, method="tx",
                            params={
                                "hash": base64.b64encode(tx_hash).decode()
                            },
                        )["result"]
                    except Exception:
                        time.sleep(0.2)
                assert got is not None and int(got["height"]) == tx_height
                found = _rpc_post(
                    port=rpc_port, method="tx_search",
                    params={"query": f"tx.height={tx_height}"},
                )["result"]
                assert found["total_count"] == "1"
                assert found["txs"][0]["hash"] == tx_hash.hex().upper()
                blocks = _rpc_post(
                    port=rpc_port, method="block_search",
                    params={"query": f"block.height={tx_height}"},
                )["result"]
                assert blocks["total_count"] == "1"

                # Prometheus endpoint serves live consensus series
                import urllib.request

                scrape = urllib.request.urlopen(
                    f"http://127.0.0.1:{prom_port}/metrics", timeout=5
                ).read().decode()
                assert "cometbft_consensus_height" in scrape
                assert "cometbft_consensus_total_txs" in scrape
                assert "cometbft_mempool_size" in scrape
                assert "cometbft_state_block_processing_time_count" in scrape
            finally:
                node.stop()

    def test_new_rpc_routes_end_to_end(self):
        """block_results / check_tx / genesis_chunked / tx(prove=true)
        with client-side Merkle verification / WS subscription client /
        broadcast_evidence / gRPC BroadcastAPI / unsafe-route gating —
        the round-4 RPC surface, driven against a live node."""
        from cometbft_tpu.cmd.commands import _load_config
        from cometbft_tpu.node import default_new_node

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "rpc-routes"])
            rpc_port, p2p_port, grpc_port = _free_ports(3)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.rpc.grpc_laddr = f"tcp://127.0.0.1:{grpc_port}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            cfg.consensus.timeout_commit_ns = 200_000_000
            node = default_new_node(cfg)
            node.start()
            try:
                # WS subscription: see a NewBlock arrive (no polling)
                from cometbft_tpu.rpc.client import WSClient

                deadline = time.monotonic() + 60
                ws = None
                while time.monotonic() < deadline and ws is None:
                    try:
                        ws = WSClient(f"127.0.0.1:{rpc_port}")
                        ws.connect()
                    except OSError:
                        ws = None
                        time.sleep(0.3)
                assert ws is not None, "ws never connected"
                sub = ws.subscribe("tm.event='NewBlock'")
                ev = sub.next(timeout=60)
                assert ev["data"]["type"] == "EventDataNewBlock"
                height = int(ev["data"]["value"]["block"]["header"]["height"])
                assert height >= 1

                # commit a tx, then block_results serves its DeliverTx
                tx_b = b"route=42"
                res = _rpc_post(
                    port=rpc_port, method="broadcast_tx_commit",
                    params={"tx": base64.b64encode(tx_b).decode()},
                )["result"]
                assert res["deliver_tx"]["code"] == 0
                txh = int(res["height"])
                br = _rpc_post(
                    port=rpc_port, method="block_results",
                    params={"height": txh},
                )["result"]
                assert br["height"] == str(txh)
                assert len(br["txs_results"]) == 1
                assert br["txs_results"][0]["code"] == 0

                # check_tx probes without mutating the mempool
                ct = _rpc_post(
                    port=rpc_port, method="check_tx",
                    params={"tx": base64.b64encode(b"probe=1").decode()},
                )["result"]
                assert ct["code"] == 0
                n_un = _rpc(rpc_port, "num_unconfirmed_txs")["result"]
                assert n_un["total"] == "0"

                # genesis_chunked reassembles to the genesis doc
                gc = _rpc_post(
                    port=rpc_port, method="genesis_chunked",
                    params={"chunk": 0},
                )["result"]
                assert gc["total"] == "1"
                doc = json.loads(base64.b64decode(gc["data"]))
                assert doc["chain_id"] == "rpc-routes"

                # tx(prove=true): verify the Merkle proof client-side
                import hashlib as _hl

                from cometbft_tpu.crypto import merkle as merkle_mod
                from cometbft_tpu.types.tx import Tx

                deadline = time.monotonic() + 10
                got = None
                while time.monotonic() < deadline and got is None:
                    try:
                        got = _rpc_post(
                            port=rpc_port, method="tx",
                            params={
                                "hash": base64.b64encode(
                                    _hl.sha256(tx_b).digest()
                                ).decode(),
                                "prove": True,
                            },
                        )["result"]
                    except Exception:
                        time.sleep(0.2)
                assert got is not None and "proof" in got
                pj = got["proof"]
                proof = merkle_mod.Proof(
                    total=int(pj["proof"]["total"]),
                    index=int(pj["proof"]["index"]),
                    leaf_hash=base64.b64decode(pj["proof"]["leaf_hash"]),
                    aunts=[base64.b64decode(a) for a in pj["proof"]["aunts"]],
                )
                root = bytes.fromhex(pj["root_hash"])
                proof.verify(root, Tx(tx_b).hash())  # raises on mismatch
                # ... and the root is the block's data_hash
                blk = _rpc_post(
                    port=rpc_port, method="block", params={"height": txh}
                )["result"]
                assert blk["block"]["header"]["data_hash"] == pj["root_hash"]

                # broadcast_evidence: a real double-vote from the node's
                # own validator key lands in the pool and commits
                from cometbft_tpu.types.evidence import (
                    DuplicateVoteEvidence,
                    encode_evidence,
                )
                from cometbft_tpu.types.test_util import MockPV, make_vote
                from cometbft_tpu.types.block import BlockID, PartSetHeader
                from cometbft_tpu.proto.gogo import Timestamp as _Ts

                pv = MockPV(node.priv_validator.priv_key)
                meta1 = node.block_store.load_block_meta(1)
                bt = meta1.header.time
                bid_a = BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xbb" * 32))
                bid_b = BlockID(b"\xcc" * 32, PartSetHeader(1, b"\xdd" * 32))
                v1 = make_vote(pv, "rpc-routes", 0, 1, 0, 1, bid_a, bt)
                v2 = make_vote(pv, "rpc-routes", 0, 1, 0, 1, bid_b, bt)
                ev_obj = DuplicateVoteEvidence.new(
                    v1, v2, bt, node.state_store.load_validators(1)
                )
                out = _rpc_post(
                    port=rpc_port, method="broadcast_evidence",
                    params={
                        "evidence": base64.b64encode(
                            encode_evidence(ev_obj)
                        ).decode()
                    },
                )["result"]
                assert out["hash"] == ev_obj.hash().hex().upper()
                # garbage evidence is a clean RPC error, not a 500
                bad = _rpc_post(
                    port=rpc_port, method="broadcast_evidence",
                    params={"evidence": base64.b64encode(b"junk").decode()},
                )
                assert "error" in bad

                # unsafe routes are refused without [rpc] unsafe
                flush = _rpc_post(
                    port=rpc_port, method="unsafe_flush_mempool", params={}
                )
                assert "error" in flush

                # gRPC BroadcastAPI: ping + a tx end to end
                from cometbft_tpu.rpc.grpc_api import BroadcastAPIClient

                gclient = BroadcastAPIClient(f"127.0.0.1:{grpc_port}")
                gclient.start()
                try:
                    gclient.ping()
                    gres = gclient.broadcast_tx(b"grpc=yes")
                    assert gres.check_tx is not None
                    assert gres.check_tx.code == 0
                    assert gres.deliver_tx is not None
                    assert gres.deliver_tx.code == 0
                finally:
                    gclient.stop()

                ws.close()
            finally:
                node.stop()

    def test_statesync_failure_falls_back_instead_of_wedging(self):
        """A dead statesync (no snapshots / provider failure) must not
        leave the node in wait-sync forever: it falls back to
        blocksync/consensus with the state_syncing gauge cleared
        (ADVICE r3; reference treats startStateSync failure as fatal)."""
        from cometbft_tpu.cmd.commands import _load_config
        from cometbft_tpu.node import default_new_node

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "ss-fail"])
            rpc_port, p2p_port = _free_ports(2)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            cfg.consensus.timeout_commit_ns = 100_000_000
            cfg.statesync.enable = True
            node = default_new_node(cfg)

            def boom(*a, **k):
                raise RuntimeError("no snapshots anywhere")

            node.statesync_reactor.sync = boom
            node.state_provider = object()  # skip config-derived provider
            node.start()
            try:
                metrics = node.consensus_state.metrics
                deadline = time.monotonic() + 60
                height = 0
                while time.monotonic() < deadline and height < 2:
                    try:
                        height = int(
                            _rpc(rpc_port, "status")["result"]["sync_info"][
                                "latest_block_height"
                            ]
                        )
                    except Exception:
                        pass
                    time.sleep(0.3)
                assert height >= 2, "node wedged after statesync failure"
                assert metrics.state_syncing.value() == 0
            finally:
                node.stop()

    def test_node_restarts_from_disk(self):
        """Stop the node, boot a second one from the same home: state,
        blocks, and the privval sign state all survive (handshake replay)."""
        from cometbft_tpu.cmd.commands import _load_config
        from cometbft_tpu.node import default_new_node

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "restart-test"])
            rpc_port, p2p_port = _free_ports(2)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = ""
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            node = default_new_node(cfg)
            node.start()
            try:
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    if node.block_store.height() >= 3:
                        break
                    time.sleep(0.2)
                h1 = node.block_store.height()
                assert h1 >= 3
            finally:
                node.stop()
            time.sleep(0.5)

            node2 = default_new_node(cfg)
            node2.start()
            try:
                assert node2.block_store.height() >= h1
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    if node2.block_store.height() > h1 + 1:
                        break
                    time.sleep(0.2)
                assert node2.block_store.height() > h1 + 1, (
                    "restarted node made no progress"
                )
            finally:
                node2.stop()


def _ws_recv_frame(sock):
    hdr = sock.recv(2)
    if len(hdr) < 2:
        raise ConnectionError("ws closed")
    length = hdr[1] & 0x7F
    if length == 126:
        import struct

        (length,) = struct.unpack(">H", sock.recv(2))
    elif length == 127:
        import struct

        (length,) = struct.unpack(">Q", sock.recv(8))
    buf = b""
    while len(buf) < length:
        chunk = sock.recv(length - len(buf))
        if not chunk:
            raise ConnectionError("ws closed mid-frame")
        buf += chunk
    return buf


def _ws_send_text(sock, text: bytes):
    import struct

    mask = os.urandom(4)
    payload = bytes(c ^ mask[i % 4] for i, c in enumerate(text))
    n = len(text)
    if n < 126:
        header = bytes([0x81, 0x80 | n])
    else:
        header = bytes([0x81, 0x80 | 126]) + struct.pack(">H", n)
    sock.sendall(header + mask + payload)


@pytest.mark.slow
class TestLocalnet:
    def test_four_node_localnet_commits_tx(self):
        """The VERDICT's done-criterion: `testnet` + 4 × `start` processes,
        a tx submitted over RPC to node0 is committed and readable on
        node3; a WS subscriber on node1 sees NewBlock events."""
        with tempfile.TemporaryDirectory() as d:
            ports = _free_ports(8)
            p2p_ports, rpc_ports = ports[:4], ports[4:]
            out = os.path.join(d, "net")
            # testnet with explicit port bases won't match random free
            # ports — generate, then patch each config
            assert cli_main(
                ["testnet", "--v", "4", "--output-dir", out,
                 "--chain-id", "localnet"]
            ) == 0
            from cometbft_tpu.cmd.commands import _load_config
            from cometbft_tpu.config import write_config_file
            from cometbft_tpu.p2p.key import NodeKey

            ids = [
                NodeKey.load_or_gen(
                    os.path.join(out, f"node{i}", "config", "node_key.json")
                ).id()
                for i in range(4)
            ]
            for i in range(4):
                home = os.path.join(out, f"node{i}")
                cfg = _load_config(home)
                cfg.base.proxy_app = "kvstore"
                cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_ports[i]}"
                cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_ports[i]}"
                cfg.p2p.persistent_peers = ",".join(
                    f"{ids[j]}@127.0.0.1:{p2p_ports[j]}"
                    for j in range(4)
                    if j != i
                )
                write_config_file(
                    os.path.join(home, "config", "config.toml"), cfg
                )

            procs = []
            try:
                for i in range(4):
                    procs.append(
                        subprocess.Popen(
                            [
                                sys.executable, "-m", "cometbft_tpu",
                                "--home", os.path.join(out, f"node{i}"),
                                "start",
                            ],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT,
                            text=True,
                        )
                    )
                # all four reach height 2
                deadline = time.monotonic() + 180
                heights = [0] * 4
                while time.monotonic() < deadline:
                    for i in range(4):
                        try:
                            st = _rpc(rpc_ports[i], "status", timeout=2)
                            heights[i] = int(
                                st["result"]["sync_info"]["latest_block_height"]
                            )
                        except Exception:
                            pass
                    if all(h >= 2 for h in heights):
                        break
                    time.sleep(0.5)
                assert all(h >= 2 for h in heights), (
                    f"localnet stuck at {heights}"
                )

                # WS subscribe on node1 for NewBlock
                ws = socket.create_connection(
                    ("127.0.0.1", rpc_ports[1]), timeout=10
                )
                key = base64.b64encode(os.urandom(16)).decode()
                ws.sendall(
                    (
                        f"GET /websocket HTTP/1.1\r\n"
                        f"Host: 127.0.0.1\r\nUpgrade: websocket\r\n"
                        f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
                        f"Sec-WebSocket-Version: 13\r\n\r\n"
                    ).encode()
                )
                resp = b""
                while b"\r\n\r\n" not in resp:
                    resp += ws.recv(1024)
                assert b"101" in resp.split(b"\r\n")[0]
                _ws_send_text(
                    ws,
                    json.dumps(
                        {
                            "jsonrpc": "2.0", "id": 7, "method": "subscribe",
                            "params": {"query": "tm.event='NewBlock'"},
                        }
                    ).encode(),
                )
                ws.settimeout(30)
                ack = json.loads(_ws_recv_frame(ws))
                assert ack["id"] == 7 and "result" in ack

                # tx to node0 → committed → readable on node3
                tx = base64.b64encode(b"lk=lv").decode()
                res = _rpc_post(
                    rpc_ports[0], "broadcast_tx_commit", {"tx": tx},
                    timeout=60,
                )["result"]
                assert res["deliver_tx"]["code"] == 0, res

                deadline = time.monotonic() + 60
                val = None
                while time.monotonic() < deadline:
                    q = _rpc(
                        rpc_ports[3],
                        "abci_query?path=/store&data=0x" + b"lk".hex(),
                        timeout=5,
                    )["result"]["response"]
                    if q["value"]:
                        val = base64.b64decode(q["value"])
                        break
                    time.sleep(0.5)
                assert val == b"lv", "tx not visible on node3"

                # the WS subscriber saw at least one NewBlock
                ev = json.loads(_ws_recv_frame(ws))
                assert ev["result"]["query"] == "tm.event='NewBlock'"
                ws.close()
            finally:
                for p in procs:
                    p.send_signal(signal.SIGTERM)
                for p in procs:
                    try:
                        p.communicate(timeout=15)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.communicate()


class TestThreadHygiene:
    """leaktest analog (the reference wraps tests in leaktest.Check):
    a stopped node must not leave non-daemon threads behind — a leaked
    thread means stop() misses a service and shutdown would hang."""

    def test_node_start_stop_leaves_no_nondaemon_threads(self, tmp_path):
        import threading
        import time

        from cometbft_tpu.cmd.commands import main as cli_main, _load_config
        from cometbft_tpu.libs.net import free_ports
        from cometbft_tpu.node import default_new_node

        def nondaemon():
            return {
                t for t in threading.enumerate()
                if not t.daemon and t.is_alive()
            }

        home = str(tmp_path / "leaknode")
        cli_main(["--home", home, "init", "--chain-id", "leak-chain"])
        cfg = _load_config(home)
        p2p, rpc = free_ports(2)
        cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc}"
        cfg.base.proxy_app = "kvstore"
        baseline = nondaemon()
        for _ in range(2):  # twice: catches leaks that survive restart
            node = default_new_node(cfg)
            node.start()
            time.sleep(1.0)
            node.stop()
            deadline = time.monotonic() + 20
            leaked = nondaemon() - baseline
            while leaked and time.monotonic() < deadline:
                time.sleep(0.25)
                leaked = nondaemon() - baseline
            assert not leaked, (
                f"non-daemon threads leaked after stop: "
                f"{[t.name for t in leaked]}"
            )


class TestAddrBookWiring:
    def test_our_address_and_private_ids_excluded(self):
        """Reference createAddrBookAndSetOnSwitch: the node's own
        advertised address and operator-marked private peers must never
        enter the address book (self-dial guard; sentry privacy —
        without the wiring the private_peer_ids knob is inert)."""
        from cometbft_tpu.cmd.commands import _load_config
        from cometbft_tpu.node import default_new_node
        from cometbft_tpu.p2p.netaddr import NetAddress

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "ab-wire"])
            rpc_port, p2p_port = _free_ports(2)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = ""
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            cfg.p2p.addr_book_strict = False
            private_id = "ab" * 20
            cfg.p2p.private_peer_ids = private_id
            node = default_new_node(cfg)
            book = node.addr_book
            assert book is not None
            src = NetAddress("cd" * 20, "127.0.0.1", 40001)
            # a peer gossiping our own address back: silently dropped
            ours = NetAddress(node.node_key.id(), "127.0.0.1", p2p_port)
            book.add_address(ours, src)
            assert not book.has_address(ours)
            # a private peer's address: never enters the book
            priv = NetAddress(private_id, "127.0.0.1", 40002)
            book.add_address(priv, src)
            assert not book.has_address(priv)
            # an ordinary peer still lands
            ok = NetAddress("ef" * 20, "127.0.0.1", 40003)
            book.add_address(ok, src)
            assert book.has_address(ok)
            # addresses learned FROM a private peer are rejected too
            # (reference ErrAddrBookPrivateSrc)
            priv_src = NetAddress(private_id, "127.0.0.1", 40002)
            import pytest as _pytest

            with _pytest.raises(ValueError):
                book.add_address(
                    NetAddress("12" * 20, "127.0.0.1", 40004), priv_src
                )


class TestGenesisHashPinning:
    def test_changed_genesis_refuses_existing_data(self):
        """node.go:1394-1449: the genesis doc's hash is pinned in the
        state DB on first boot; booting the same home against a DIFFERENT
        genesis must fail up front instead of diverging on app hashes."""
        from cometbft_tpu.cmd.commands import _load_config
        from cometbft_tpu.node import default_new_node

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "gen-pin"])
            (p2p_port,) = _free_ports(1)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = ""
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            node = default_new_node(cfg)  # first boot pins the hash
            node._abort_init()  # constructed-but-unstarted teardown
            # same genesis: boots fine (raw-file hash is stable)
            node2 = default_new_node(cfg)
            node2._abort_init()
            # tamper with genesis (different chain id)
            gp = os.path.join(d, "config", "genesis.json")
            raw = open(gp).read().replace("gen-pin", "gen-pin-2")
            open(gp, "w").write(raw)
            with pytest.raises(ValueError, match="genesis doc hash"):
                default_new_node(cfg)


class TestSubscriptionLimits:
    def test_per_client_subscription_cap(self):
        """rpc/core/events.go Subscribe: max_subscriptions_per_client is
        enforced at subscribe time (the knob was previously inert)."""
        from cometbft_tpu.cmd.commands import _load_config
        from cometbft_tpu.node import default_new_node
        from cometbft_tpu.rpc.client import RPCClientError, WSClient

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "sub-cap"])
            rpc_port, p2p_port = _free_ports(2)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.rpc.max_subscriptions_per_client = 2
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            cfg.consensus.timeout_commit_ns = 200_000_000
            node = default_new_node(cfg)
            node.start()
            ws = None
            try:
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline and ws is None:
                    try:
                        ws = WSClient(f"127.0.0.1:{rpc_port}")
                        ws.connect()
                    except OSError:
                        ws = None
                        time.sleep(0.3)
                assert ws is not None
                ws.subscribe("tm.event='NewBlock'")
                ws.subscribe("tm.event='Tx'")
                with pytest.raises(RPCClientError, match="per_client"):
                    ws.subscribe("tm.event='NewBlockHeader'")
            finally:
                if ws is not None:
                    try:
                        ws.close()
                    except Exception:
                        pass
                node.stop()
