"""Deadlock-detection watchdog (libs/sync deadlock.go analog)."""

import io
import sys
import threading
import time

from cometbft_tpu.libs import deadlock


class TestDeadlockDetector:
    def test_disabled_by_default_and_reversible(self):
        assert not deadlock.is_enabled()
        orig = threading.Lock
        deadlock.enable(timeout_s=0.5)
        try:
            assert deadlock.is_enabled()
            assert threading.Lock is not orig
        finally:
            deadlock.disable()
        assert threading.Lock is orig

    def test_wrapped_locks_behave_normally(self):
        deadlock.enable(timeout_s=5.0)
        try:
            lk = threading.Lock()
            with lk:
                assert lk.locked()
            assert not lk.locked()
            assert lk.acquire(False)
            lk.release()
            rlk = threading.RLock()
            with rlk:
                with rlk:  # reentrant
                    pass
        finally:
            deadlock.disable()

    def test_stuck_acquire_dumps_stacks(self):
        deadlock.enable(timeout_s=0.4)
        try:
            lk = threading.Lock()
            lk.acquire()
            captured = io.StringIO()
            orig_err = sys.stderr
            sys.stderr = captured

            def waiter():
                lk.acquire(True, 1.2)  # bounded so the thread exits

            got = {}

            def blocking_waiter():
                # the unbounded acquire path is the detecting one
                t0 = time.monotonic()
                deadline_dump = None
                # run acquire in this thread; release after the dump fires
                lk.acquire()
                got["waited"] = time.monotonic() - t0
                lk.release()

            t = threading.Thread(target=blocking_waiter, daemon=True)
            t.start()
            time.sleep(1.0)  # > timeout: the dump must have fired
            sys.stderr = orig_err
            lk.release()
            t.join(5.0)
            out = captured.getvalue()
            assert "POTENTIAL DEADLOCK" in out
            assert "blocking_waiter" in out or "Thread-" in out
            assert got["waited"] >= 0.4
        finally:
            sys.stderr = orig_err
            deadlock.disable()
