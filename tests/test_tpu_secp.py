"""TPU batched secp256k1 — bit-identical parity with the CPU verifier.

The stretch companion to the ed25519 north-star kernel (SURVEY.md §2.1):
accept/reject from the JAX batch kernel must match
crypto/secp256k1.py's PubKeySecp256k1.verify_signature on valid,
corrupted, and adversarial edge-case signatures, including the low-S
malleability rule. Runs on the virtual CPU platform (conftest.py).
"""

import random

import numpy as np
import pytest

from cometbft_tpu.crypto import secp256k1 as secp
from cometbft_tpu.crypto.tpu import secp256k1_batch, secp_field as F


def _cpu_verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    return secp.PubKeySecp256k1(pk).verify_signature(msg, sig)


def _assert_parity(pks, msgs, sigs):
    got = secp256k1_batch.verify_batch(pks, msgs, sigs)
    want = [_cpu_verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    assert got == want, f"mismatch: tpu={got} cpu={want}"
    return got


class TestSecpField:
    def _fe1(self, n):
        import jax.numpy as jnp

        return jnp.array(F.int_to_limbs(n % F.P), jnp.int32)[:, None]

    def _val(self, x):
        return F.limbs_to_int(np.asarray(F.to_canonical(x))[:, 0])

    def test_ops_parity(self):
        rng = random.Random(7)
        for _ in range(15):
            a, b = rng.randrange(F.P), rng.randrange(F.P)
            fa, fb = self._fe1(a), self._fe1(b)
            assert self._val(F.add(fa, fb)) == (a + b) % F.P
            assert self._val(F.sub(fa, fb)) == (a - b) % F.P
            assert self._val(F.mul(fa, fb)) == (a * b) % F.P

    def test_chained_compositions_preserve_invariant(self):
        rng = random.Random(11)
        for trial in range(6):
            ints = [rng.randrange(F.P) for _ in range(6)]
            fes = [self._fe1(v) for v in ints]
            x, xi = fes[0], ints[0]
            for i in range(1, 6):
                op = (trial + i) % 3
                if op == 0:
                    x, xi = F.mul(x, fes[i]), xi * ints[i] % F.P
                elif op == 1:
                    x, xi = F.add(x, fes[i]), (xi + ints[i]) % F.P
                else:
                    x, xi = F.sub(x, fes[i]), (xi - ints[i]) % F.P
            assert self._val(x) == xi, trial

    def test_invert_and_sqrt(self):
        inv = F.invert(self._fe1(987654321))
        assert self._val(inv) * 987654321 % F.P == 1
        s = self._val(F.sqrt_candidate(self._fe1(9)))
        assert pow(s, 2, F.P) == 9

    def test_identity_chain_stays_bounded(self):
        """The radix-14 redesign exists exactly for this: long identity-
        doubling chains must not inflate limbs past the invariant."""
        import jax.numpy as jnp

        ident = tuple(
            jnp.broadcast_to(c, (F.NUM_LIMBS, 1))
            for c in (F.const_fe(0), F.const_fe(1), F.const_fe(0))
        )
        acc = ident
        for i in range(64):
            acc = secp256k1_batch.point_dbl(acc)
            assert self._val(acc[0]) == 0 and self._val(acc[2]) == 0, i
            m = max(int(np.abs(np.asarray(c)).max()) for c in acc)
            assert m < (1 << F.RADIX) + 4096, (i, m)


class TestSecpVerifyParity:
    @pytest.fixture(scope="class")
    def keys(self):
        return [secp.gen_priv_key() for _ in range(6)]

    def test_valid_and_corrupted(self, keys):
        pks, msgs, sigs = [], [], []
        for i, k in enumerate(keys):
            m = b"secp vote %d" % i
            s = bytearray(k.sign(m))
            if i % 3 == 1:
                s[10] ^= 1
            pks.append(k.pub_key().bytes())
            msgs.append(m)
            sigs.append(bytes(s))
        got = _assert_parity(pks, msgs, sigs)
        assert got[0] and not got[1]

    def test_wrong_key_and_message(self, keys):
        k1, k2 = keys[0], keys[1]
        m = b"proposal"
        sig = k1.sign(m)
        _assert_parity(
            [k2.pub_key().bytes(), k1.pub_key().bytes()],
            [m, b"other message"],
            [sig, sig],
        )

    def test_high_s_rejected(self, keys):
        """The low-S rule: flipping s to n - s keeps the curve equation
        satisfied but MUST be rejected (malleability)."""
        k = keys[0]
        m = b"malleable"
        sig = k.sign(m)
        r = sig[:32]
        s = int.from_bytes(sig[32:], "big")
        high = r + (F.N - s).to_bytes(32, "big")
        got = _assert_parity(
            [k.pub_key().bytes()] * 2, [m, m], [sig, high]
        )
        assert got == [True, False]

    def test_structural_garbage(self, keys):
        k = keys[0]
        m = b"m"
        good = k.sign(m)
        zero_r = bytes(32) + good[32:]
        zero_s = good[:32] + bytes(32)
        big_r = F.N.to_bytes(32, "big") + good[32:]
        bad_prefix = b"\x05" + k.pub_key().bytes()[1:]
        x_too_big = bytes([2]) + F.P.to_bytes(32, "big")
        not_on_curve = bytes([2]) + (5).to_bytes(32, "big")
        pks = [k.pub_key().bytes()] * 3 + [bad_prefix, x_too_big, not_on_curve]
        sigs = [zero_r, zero_s, big_r, good, good, good]
        got = _assert_parity(pks, [m] * 6, sigs)
        assert not any(got)

    def test_wrong_lengths_and_empty(self, keys):
        got = secp256k1_batch.verify_batch(
            [b"short", keys[0].pub_key().bytes()],
            [b"m", b"m"],
            [b"\x01" * 64, b"\x01" * 63],
        )
        assert got == [False, False]
        assert secp256k1_batch.verify_batch([], [], []) == []


class TestMixedCurveBatch:
    def test_partitioned_by_curve_through_boundary(self):
        """SURVEY §7 stage 10: one batch holding ed25519 AND secp keys,
        each partition on its own kernel, per-sig mask exact."""
        from cometbft_tpu.crypto import ed25519 as ed
        from cometbft_tpu.crypto.batch import TPUBatchVerifier

        bv = TPUBatchVerifier(min_batch=1, secp_min_batch=1)
        expect = []
        for i in range(4):
            k = ed.gen_priv_key_from_secret(bytes([i, 31]))
            m = b"ed %d" % i
            sig = k.sign(m) if i != 1 else b"\x0a" * 64
            bv.add(k.pub_key(), m, sig)
            expect.append(i != 1)
        for i in range(4):
            k = secp.gen_priv_key()
            m = b"secp %d" % i
            s = bytearray(k.sign(m))
            if i == 2:
                s[5] ^= 1
            bv.add(k.pub_key(), m, bytes(s))
            expect.append(
                secp.PubKeySecp256k1(k.pub_key().bytes()).verify_signature(
                    m, bytes(s)
                )
            )
        ok, mask = bv.verify()
        assert mask == expect
        assert not ok


class TestCompactWireUnpack:
    """Device-side unpack of the compact secp wire vs independent
    oracles — the wire is the dispatch ABI; a bit-slip corrupts every
    lane (same contract as the ed25519 unpack tests)."""

    def test_fe_limbs_match_int_oracle(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(17)
        raw = rng.integers(0, 256, size=(9, 32)).astype(np.uint8)
        words = jnp.asarray(secp256k1_batch._le_words(raw))
        got = np.asarray(secp256k1_batch.unpack_fe_limbs(words))
        for b in range(raw.shape[0]):
            val = int.from_bytes(raw[b].tobytes(), "little")
            assert F.limbs_to_int(got[:, b]) == val, b
            assert all(0 <= int(v) < 2**F.RADIX for v in got[:, b])
        # cross-check against the host limb oracle (expects BE bytes)
        want = F.bytes_be_to_limbs_np(raw[:, ::-1]).T
        assert (got == want).all()

    def test_digits_match_bit_oracle(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(19)
        raw = rng.integers(0, 256, size=(7, 32)).astype(np.uint8)
        words = jnp.asarray(secp256k1_batch._le_words(raw))
        got = np.asarray(secp256k1_batch.unpack_digits(words))
        bits = np.unpackbits(raw, axis=-1, bitorder="little")
        digits = bits[:, 0:256:2] + 2 * bits[:, 1:256:2]  # LSB-first pairs
        want = np.ascontiguousarray(digits[:, ::-1].astype(np.int32).T)
        assert (got == want).all()

    def test_flags_encode_parity_and_rn(self):
        k = secp.gen_priv_key()
        m = b"wire flags"
        sig = k.sign(m)
        pk = k.pub_key().bytes()
        wire, flags, valid = secp256k1_batch.prepare_batch(
            [pk], [m], [sig]
        )
        assert valid[0]
        assert wire.shape == (32, 1) and wire.dtype == np.uint32
        assert int(flags[0]) & 1 == pk[0] & 1
        r = int.from_bytes(sig[:32], "big")
        assert bool(int(flags[0]) & 2) == (r + F.N < F.P)
        # wire rows carry qx, r, u1, u2 as raw LE words
        qx = int.from_bytes(
            np.asarray(wire[0:8, 0]).astype("<u4").tobytes(), "little"
        )
        assert qx == int.from_bytes(pk[1:], "big")
        r_w = int.from_bytes(
            np.asarray(wire[8:16, 0]).astype("<u4").tobytes(), "little"
        )
        assert r_w == r
