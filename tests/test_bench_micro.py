"""bench_micro.py harness smoke: every section must produce numeric
results (parity with the reference's harness-only Go benchmarks —
values are machine-dependent and never asserted)."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.parametrize(
    "section",
    [
        "coldboot", "ed25519", "validator_set", "light", "mempool",
        "routing", "scheduler", "telemetry", "wal",
    ],
)
def test_section_produces_numbers(section):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench_micro.py"), section],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr[-400:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["section"] == section
    assert "error" not in out, out
    numeric = [
        v for k, v in out.items() if isinstance(v, (int, float)) and k != "section"
    ]
    assert numeric and all(v > 0 for v in numeric), out
