"""Multi-device dispatch of the crypto plane (crypto/tpu/mesh.py).

Runs on the virtual 8-device CPU mesh (conftest's
xla_force_host_platform_device_count=8): verify_batch must route
through the sharded program automatically and stay bit-identical.
"""

import numpy as np

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.tpu import ed25519_batch, mesh


class TestMeshDispatch:
    def test_eight_virtual_devices_visible(self):
        assert mesh.n_devices() == 8
        m = mesh.batch_mesh()
        assert m.devices.shape == (8,)
        assert m.axis_names == ("batch",)

    def test_verify_batch_shards_and_matches_serial(self):
        keys = [ed.gen_priv_key_from_secret(bytes([i, 55])) for i in range(40)]
        pks, msgs, sigs = [], [], []
        for i, k in enumerate(keys):
            m = b"mesh vote %d" % i
            s = bytearray(k.sign(m))
            if i % 5 == 0:
                s[7] ^= 1
            pks.append(k.pub_key().bytes())
            msgs.append(m)
            sigs.append(bytes(s))
        got = ed25519_batch.verify_batch(pks, msgs, sigs)  # 40 → pad 64 = 8×8
        want = [
            ed.PubKeyEd25519(p).verify_signature(m, s)
            for p, m, s in zip(pks, msgs, sigs)
        ]
        assert got == want

    def test_sharded_kernel_cache_reused(self):
        before = dict(mesh._sharded_kernels)
        pks, msgs, sigs = [], [], []
        for i in range(8):
            k = ed.gen_priv_key_from_secret(bytes([i, 66]))
            m = b"again %d" % i
            pks.append(k.pub_key().bytes())
            msgs.append(m)
            sigs.append(k.sign(m))
        assert all(ed25519_batch.verify_batch(pks, msgs, sigs))
        assert all(ed25519_batch.verify_batch(pks, msgs, sigs))
        # at most one new compiled sharded program per (kernel, arity)
        assert len(mesh._sharded_kernels) <= len(before) + 1

    def test_maybe_init_distributed_noop_without_config(self, monkeypatch):
        monkeypatch.delenv("CBFT_TPU_COORDINATOR", raising=False)
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        assert mesh.maybe_init_distributed() is False
