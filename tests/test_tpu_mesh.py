"""Multi-device dispatch of the crypto plane (crypto/tpu/mesh.py).

Runs on the virtual 8-device CPU mesh (conftest's
xla_force_host_platform_device_count=8): verify_batch must route
through the sharded program automatically and stay bit-identical.
"""

import threading

import numpy as np

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.tpu import ed25519_batch, mesh, topology


class TestMeshDispatch:
    def test_eight_virtual_devices_visible(self):
        assert mesh.n_devices() == 8
        m = mesh.batch_mesh()
        assert m.devices.shape == (8,)
        assert m.axis_names == ("batch",)

    def test_verify_batch_shards_and_matches_serial(self):
        keys = [ed.gen_priv_key_from_secret(bytes([i, 55])) for i in range(40)]
        pks, msgs, sigs = [], [], []
        for i, k in enumerate(keys):
            m = b"mesh vote %d" % i
            s = bytearray(k.sign(m))
            if i % 5 == 0:
                s[7] ^= 1
            pks.append(k.pub_key().bytes())
            msgs.append(m)
            sigs.append(bytes(s))
        got = ed25519_batch.verify_batch(pks, msgs, sigs)  # 40 → pad 64 = 8×8
        want = [
            ed.PubKeyEd25519(p).verify_signature(m, s)
            for p, m, s in zip(pks, msgs, sigs)
        ]
        assert got == want

    def test_sharded_executable_registry_reused(self):
        from cometbft_tpu.crypto.tpu import aot

        pks, msgs, sigs = [], [], []
        for i in range(8):
            k = ed.gen_priv_key_from_secret(bytes([i, 66]))
            m = b"again %d" % i
            pks.append(k.pub_key().bytes())
            msgs.append(m)
            sigs.append(k.sign(m))
        reg = aot.default_registry()
        assert all(ed25519_batch.verify_batch(pks, msgs, sigs))
        compiles = reg.compile_count
        entries = len(reg)
        hits = reg.metrics.registry_hits.value()
        # the repeat dispatch lands on the SAME (kernel, bucket,
        # topology, backend) registry key: zero new executables
        assert all(ed25519_batch.verify_batch(pks, msgs, sigs))
        assert reg.compile_count == compiles
        assert len(reg) == entries
        assert reg.metrics.registry_hits.value() > hits

    def test_maybe_init_distributed_noop_without_config(self, monkeypatch):
        monkeypatch.delenv("CBFT_TPU_COORDINATOR", raising=False)
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        assert mesh.maybe_init_distributed() is False


class TestDispatchChunking:
    """The callable `packed` form (per-chunk packing for host/device
    overlap) must chunk, pad, and reassemble identically to the
    pre-packed array form, including the multi-chunk path."""

    def _toy_kernel(self):
        import jax

        @jax.jit
        def parity_kernel(rows):
            # bool[B]: even column sums — shape-preserving stand-in for a
            # verify kernel ([R, B] in, bool[B] out)
            return (rows.sum(axis=0) % 2) == 0

        return parity_kernel

    def test_callable_matches_array_form_across_chunks(self, monkeypatch):
        monkeypatch.delenv("CBFT_TPU_MAX_CHUNK", raising=False)
        kernel = self._toy_kernel()
        rng = np.random.default_rng(23)
        n = 50  # > max_chunk=16 → 4 chunks, last one ragged
        full = rng.integers(0, 100, size=(3, n)).astype(np.int32)

        got_arrays = mesh.dispatch_batch(kernel, [full], n, 16, 8)

        calls = []

        def chunk_pack(start, end):
            calls.append((start, end))
            return [full[:, start:end]]

        got_callable = mesh.dispatch_batch(kernel, chunk_pack, n, 16, 8)
        want = (full.sum(axis=0) % 2) == 0
        assert (got_arrays == want).all()
        assert (got_callable == want).all()
        assert calls == [(0, 16), (16, 32), (32, 48), (48, 50)]

    def test_padding_never_leaks_into_results(self, monkeypatch):
        # padded lanes compute kernel(0-columns) = True here; the slice
        # back to [start:end) must drop them even when the final chunk is
        # a single lane
        monkeypatch.delenv("CBFT_TPU_MAX_CHUNK", raising=False)
        kernel = self._toy_kernel()
        ones = np.ones((2, 17), np.int32)  # column sum 2 → even → True
        out = mesh.dispatch_batch(kernel, [ones], 17, 16, 8)
        assert out.shape == (17,) and out.all()


class TestCancelScopeIsolation:
    """cancel_scope and device_scope are strictly thread-local: a zombie
    dispatch (abandoned by the watchdog, cancel event set) exiting via
    DispatchCancelled at a chunk boundary must never cancel — or cap —
    a healthy dispatch running concurrently on another thread/device."""

    def _toy_kernel(self):
        import jax

        @jax.jit
        def parity_kernel(rows):
            return (rows.sum(axis=0) % 2) == 0

        return parity_kernel

    def test_zombie_cancel_does_not_cancel_healthy_dispatch(self, monkeypatch):
        monkeypatch.delenv("CBFT_TPU_MAX_CHUNK", raising=False)
        kernel = self._toy_kernel()
        n = 48  # 3 chunks of 16
        full = np.ones((2, n), np.int32)  # even column sums → all True
        cancel = threading.Event()
        zombie_mid_chunk = threading.Event()
        release_zombie = threading.Event()
        zombie_exc = []

        def zombie_pack(start, end):
            if start == 16:
                # wedged mid-dispatch, the way an abandoned watchdog
                # worker sits on a hung device call
                zombie_mid_chunk.set()
                release_zombie.wait(10)
            return [full[:, start:end]]

        def zombie():
            try:
                with mesh.cancel_scope(cancel):
                    mesh.dispatch_batch(kernel, zombie_pack, n, 16, 8)
            except BaseException as exc:  # noqa: BLE001 - recorded for assert
                zombie_exc.append(exc)

        zt = threading.Thread(target=zombie, daemon=True, name="zombie")
        zt.start()
        assert zombie_mid_chunk.wait(10)
        cancel.set()  # the watchdog abandons the zombie

        # a healthy dispatch on ANOTHER thread and device, overlapping
        # both the wedged window and the zombie's cancelled exit
        topo = topology.DeviceTopology.virtual(2)
        healthy_out = {}

        def healthy():
            with topology.device_scope(topo.device(1)):
                healthy_out["mask"] = mesh.dispatch_batch(
                    kernel, [full], n, 16, 8
                )

        ht = threading.Thread(target=healthy, name="healthy")
        ht.start()
        ht.join(30)
        release_zombie.set()  # zombie resumes → next chunk boundary raises
        zt.join(30)
        assert not ht.is_alive() and not zt.is_alive()
        assert len(zombie_exc) == 1
        assert isinstance(zombie_exc[0], mesh.DispatchCancelled)
        # the healthy dispatch never saw the zombie's cancel event
        assert healthy_out["mask"].shape == (n,)
        assert healthy_out["mask"].all()

    def test_device_param_selects_that_devices_chunk_cap(self, monkeypatch):
        monkeypatch.delenv("CBFT_TPU_MAX_CHUNK", raising=False)
        kernel = self._toy_kernel()
        topo = topology.DeviceTopology.virtual(2)
        topo.device(1).shrink_chunk_cap()  # dev1: 16 → 8
        full = np.ones((2, 32), np.int32)
        calls = []

        def pack(start, end):
            calls.append((start, end))
            return [full[:, start:end]]

        out = mesh.dispatch_batch(
            kernel, pack, 32, 16, 8, device=topo.device(1)
        )
        assert out.all()
        assert calls == [(0, 8), (8, 16), (16, 24), (24, 32)]
        calls.clear()
        # the unshrunk neighbor keeps the full cap
        out = mesh.dispatch_batch(
            kernel, pack, 32, 16, 8, device=topo.device(0)
        )
        assert out.all()
        assert calls == [(0, 16), (16, 32)]

    def test_thread_scope_supplies_device_when_param_omitted(self, monkeypatch):
        monkeypatch.delenv("CBFT_TPU_MAX_CHUNK", raising=False)
        kernel = self._toy_kernel()
        topo = topology.DeviceTopology.virtual(2)
        topo.device(1).shrink_chunk_cap()
        full = np.ones((2, 32), np.int32)
        calls = []

        def pack(start, end):
            calls.append((start, end))
            return [full[:, start:end]]

        with topology.device_scope(topo.device(1)):
            assert mesh.dispatch_batch(kernel, pack, 32, 16, 8).all()
        assert calls == [(0, 8), (8, 16), (16, 24), (24, 32)]


class TestDispatchKnobs:
    """Resolution of the dispatch tuning knobs: CBFT_TPU_MAX_CHUNK env >
    configured [crypto] max_chunk > per-curve default, power-of-two
    rounding, and pipeline-depth validation."""

    @staticmethod
    def _clean(monkeypatch):
        monkeypatch.delenv("CBFT_TPU_MAX_CHUNK", raising=False)
        monkeypatch.delenv("CBFT_TPU_PIPELINE_DEPTH", raising=False)

    def test_default_when_nothing_configured(self, monkeypatch):
        self._clean(monkeypatch)
        mesh.configure_chunk_cap(None)
        assert mesh.chunk_cap(8192, 64) == 8192

    def test_configured_cap_beats_default_and_rounds_up(self, monkeypatch):
        self._clean(monkeypatch)
        mesh.configure_chunk_cap(100)  # → next pow2 bucket = 128
        try:
            assert mesh.chunk_cap(8192, 64) == 128
        finally:
            mesh.configure_chunk_cap(None)

    def test_configured_cap_below_min_pad_clamps(self, monkeypatch):
        self._clean(monkeypatch)
        mesh.configure_chunk_cap(3)
        try:
            assert mesh.chunk_cap(8192, 64) == 64
        finally:
            mesh.configure_chunk_cap(None)

    def test_env_beats_configured(self, monkeypatch):
        self._clean(monkeypatch)
        monkeypatch.setenv("CBFT_TPU_MAX_CHUNK", "256")
        mesh.configure_chunk_cap(100)
        try:
            assert mesh.chunk_cap(8192, 64) == 256
        finally:
            mesh.configure_chunk_cap(None)

    def test_env_validation(self, monkeypatch):
        self._clean(monkeypatch)
        import pytest

        monkeypatch.setenv("CBFT_TPU_MAX_CHUNK", "not-a-number")
        with pytest.raises(ValueError, match="not an integer"):
            mesh.chunk_cap(8192, 64)
        monkeypatch.setenv("CBFT_TPU_MAX_CHUNK", "32")
        with pytest.raises(ValueError, match="below the minimum pad"):
            mesh.chunk_cap(8192, 64)

    def test_pipeline_depth_default_and_override(self, monkeypatch):
        self._clean(monkeypatch)
        assert mesh.pipeline_depth() == 2  # double buffering
        monkeypatch.setenv("CBFT_TPU_PIPELINE_DEPTH", "4")
        assert mesh.pipeline_depth() == 4

    def test_pipeline_depth_validation(self, monkeypatch):
        import pytest

        monkeypatch.setenv("CBFT_TPU_PIPELINE_DEPTH", "0")
        with pytest.raises(ValueError, match="must be >= 1"):
            mesh.pipeline_depth()
        monkeypatch.setenv("CBFT_TPU_PIPELINE_DEPTH", "two")
        with pytest.raises(ValueError, match="not an integer"):
            mesh.pipeline_depth()

    def test_dispatch_identical_across_depths(self, monkeypatch):
        """Pipelining is a latency optimization only: depth 1 (serial
        retire) and depth 3 must produce the same reassembled output."""
        self._clean(monkeypatch)
        import jax

        @jax.jit
        def parity_kernel(rows):
            return (rows.sum(axis=0) % 2) == 0

        rng = np.random.default_rng(41)
        full = rng.integers(0, 100, size=(3, 50)).astype(np.int32)
        want = (full.sum(axis=0) % 2) == 0
        for depth in ("1", "3"):
            monkeypatch.setenv("CBFT_TPU_PIPELINE_DEPTH", depth)
            out = mesh.dispatch_batch(parity_kernel, [full], 50, 16, 8)
            assert (out == want).all(), f"depth={depth}"
