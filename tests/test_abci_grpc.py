"""ABCI over gRPC: the kvstore app served via GRPCServer, driven through
GRPCClient and the standard proxy AppConns.

Model: reference abci/client/grpc_client.go + server/grpc_server.go
(same service surface as the socket transport, exercised through the
shared client interface).
"""

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.grpc import GRPCClient, GRPCServer
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.proxy import AppConnConsensus, AppConnMempool, AppConnQuery


@pytest.fixture()
def grpc_pair():
    server = GRPCServer("127.0.0.1:0", KVStoreApplication())
    server.start()
    client = GRPCClient(f"127.0.0.1:{server.bound_port}")
    client.start()
    yield client
    client.stop()
    server.stop()


class TestABCIOverGRPC:
    def test_echo_info_roundtrip(self, grpc_pair):
        client = grpc_pair
        assert client.echo_sync("over grpc").message == "over grpc"
        info = client.info_sync(abci.RequestInfo())
        assert info.last_block_height == 0

    def test_full_block_cycle(self, grpc_pair):
        client = grpc_pair
        consensus = AppConnConsensus(client)
        mempool = AppConnMempool(client)
        query = AppConnQuery(client)

        check = mempool.check_tx_sync(abci.RequestCheckTx(tx=b"g=rpc"))
        assert check.code == abci.CODE_TYPE_OK
        consensus.begin_block_sync(abci.RequestBeginBlock())
        rr = consensus.deliver_tx_async(abci.RequestDeliverTx(tx=b"g=rpc"))
        assert rr.wait(5).value.code == abci.CODE_TYPE_OK
        consensus.end_block_sync(abci.RequestEndBlock(height=1))
        commit = consensus.commit_sync()
        assert commit.data  # app hash produced

        res = query.query_sync(abci.RequestQuery(data=b"g", path="/store"))
        assert res.value == b"rpc"
        info = query.info_sync(abci.RequestInfo())
        assert info.last_block_height == 1

    def test_snapshot_methods_exposed(self, grpc_pair):
        res = grpc_pair.list_snapshots_sync(abci.RequestListSnapshots())
        assert res.snapshots == []

    def test_connection_error_surfaces(self):
        client = GRPCClient("127.0.0.1:1")  # nothing listening
        client.start()
        try:
            import grpc as _grpc

            with pytest.raises(_grpc.RpcError):
                client.echo_sync("boom")
            assert client.error() is not None
        finally:
            client.stop()
