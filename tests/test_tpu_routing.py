"""Measurement-driven CPU↔device routing (crypto/tpu/calibrate.py and
its consumers).

Round 5's by-construction thresholds routed the Merkle mega-set onto a
device path that LOSES 4.5× on the tunneled link; routing is now gated
on a crossover table measured at node warmup. These tests pin the
contract on CPU-only CI: no table → no device claim (Merkle stays on
host, ed25519 keeps the conservative floor), a recorded table opens
routing exactly at the measured crossover, env knobs keep operator
precedence, and the resident commit path is reached through
ValidatorSet.verify_commit — including from concurrent threads racing
the resident-cache build.
"""

import hashlib
import json
import os
import threading

import pytest

from cometbft_tpu.crypto import batch as cbatch
from cometbft_tpu.crypto import merkle as cpu_merkle
from cometbft_tpu.crypto.batch import BackendSpec
from cometbft_tpu.crypto.tpu import calibrate, ed25519_batch
from cometbft_tpu.crypto.tpu import merkle as tpu_merkle
from cometbft_tpu.types import test_util
from cometbft_tpu.types.validator_set import Fraction

CHAIN_ID = "routing-chain"


@pytest.fixture
def clean_routing(monkeypatch):
    """No env overrides, no table: the fresh-node / CI posture."""
    monkeypatch.delenv("CBFT_TPU_MIN_BATCH", raising=False)
    monkeypatch.delenv("CBFT_TPU_MERKLE_MIN_LEAVES", raising=False)
    monkeypatch.delenv("CBFT_TPU_CALIBRATION", raising=False)
    calibrate.set_table_path(None)
    yield
    calibrate.set_table_path(None)


def _write_table(path, **floors):
    calibrate.save_table({"version": calibrate.TABLE_VERSION, **floors}, path)
    calibrate.set_table_path(path)


class TestCrossover:
    """_crossover: smallest measured size from which the device wins at
    every larger measured size too."""

    def test_monotonic_win_opens_at_smallest_winning_size(self):
        pts = {256: (5.0, 10.0), 512: (4.0, 10.0), 1024: (3.0, 10.0)}
        assert calibrate._crossover(pts) == 256

    def test_device_never_wins(self):
        pts = {256: (20.0, 10.0), 1024: (15.0, 10.0)}
        assert calibrate._crossover(pts) is None

    def test_lucky_window_does_not_open_lower_sizes(self):
        # device wins at 256 and 1024 but loses at 512: the mid-sweep
        # loss must cap the crossover at 1024, not 256
        pts = {256: (5.0, 10.0), 512: (20.0, 10.0), 1024: (3.0, 10.0)}
        assert calibrate._crossover(pts) == 1024

    def test_win_only_at_largest(self):
        pts = {256: (20.0, 10.0), 512: (20.0, 10.0), 1024: (3.0, 10.0)}
        assert calibrate._crossover(pts) == 1024


class TestTableIO:
    def test_roundtrip_and_floor_accessors(self, tmp_path, clean_routing):
        path = str(tmp_path / "cal.json")
        _write_table(path, merkle_min_leaves=512, ed25519_min_batch=256)
        assert calibrate.merkle_min_leaves() == 512
        assert calibrate.ed25519_min_batch() == 256

    def test_wrong_version_ignored(self, tmp_path, clean_routing):
        path = str(tmp_path / "cal.json")
        with open(path, "w") as f:
            json.dump(
                {"version": calibrate.TABLE_VERSION + 1, "merkle_min_leaves": 1},
                f,
            )
        calibrate.set_table_path(path)
        assert calibrate.load_table() is None
        assert calibrate.merkle_min_leaves() is None

    def test_garbage_file_ignored(self, tmp_path, clean_routing):
        path = str(tmp_path / "cal.json")
        with open(path, "w") as f:
            f.write("{torn write")
        calibrate.set_table_path(path)
        assert calibrate.load_table() is None

    def test_null_and_bogus_floors_mean_unproven(self, tmp_path, clean_routing):
        # device never won → crossover None; booleans/negatives likewise
        path = str(tmp_path / "cal.json")
        _write_table(path, merkle_min_leaves=None, ed25519_min_batch=-5)
        assert calibrate.merkle_min_leaves() is None
        assert calibrate.ed25519_min_batch() is None

    def test_missing_path_or_file(self, clean_routing):
        assert calibrate.table_path() is None
        assert calibrate.load_table() is None
        calibrate.set_table_path("/nonexistent/nowhere/cal.json")
        assert calibrate.load_table() is None

    def test_rerecorded_table_picked_up_without_restart(
        self, tmp_path, clean_routing
    ):
        path = str(tmp_path / "cal.json")
        _write_table(path, ed25519_min_batch=512)
        assert calibrate.ed25519_min_batch() == 512
        _write_table(path, ed25519_min_batch=128)
        # the (path, mtime) cache must notice the new file; force a
        # distinct mtime in case the fs clock granularity hid the rewrite
        st = os.stat(path)
        os.utime(path, (st.st_atime, st.st_mtime + 2))
        assert calibrate.ed25519_min_batch() == 128


class TestEd25519FloorPrecedence:
    """ed25519_routing_floor: env > configured min_batch > table > 1024."""

    def test_default_without_any_signal(self, clean_routing):
        assert cbatch.ed25519_routing_floor() == 1024

    def test_table_beats_default(self, tmp_path, clean_routing):
        _write_table(str(tmp_path / "cal.json"), ed25519_min_batch=256)
        assert cbatch.ed25519_routing_floor() == 256

    def test_config_beats_table(self, tmp_path, clean_routing):
        _write_table(str(tmp_path / "cal.json"), ed25519_min_batch=256)
        assert cbatch.ed25519_routing_floor(64) == 64

    def test_env_beats_everything(self, tmp_path, clean_routing, monkeypatch):
        _write_table(str(tmp_path / "cal.json"), ed25519_min_batch=256)
        monkeypatch.setenv("CBFT_TPU_MIN_BATCH", "7")
        assert cbatch.ed25519_routing_floor(64) == 7


class TestMerkleDeviceWins:
    def test_no_table_means_host(self, clean_routing):
        # the acceptance regression: 10k leaves must NOT route to the
        # device without a measured crossover proving the win
        assert not tpu_merkle.device_wins(10_000)
        assert not tpu_merkle.device_wins(10**9)

    def test_table_opens_routing_at_the_measured_floor(
        self, tmp_path, clean_routing
    ):
        _write_table(str(tmp_path / "cal.json"), merkle_min_leaves=512)
        assert tpu_merkle.device_wins(512)
        assert tpu_merkle.device_wins(10_000)
        assert not tpu_merkle.device_wins(511)

    def test_device_never_won_stays_host(self, tmp_path, clean_routing):
        _write_table(str(tmp_path / "cal.json"), merkle_min_leaves=None)
        assert not tpu_merkle.device_wins(10_000)

    def test_env_keeps_operator_precedence(self, clean_routing, monkeypatch):
        monkeypatch.setenv("CBFT_TPU_MERKLE_MIN_LEAVES", "128")
        assert tpu_merkle.device_wins(128)
        assert not tpu_merkle.device_wins(127)

    def test_host_tree_used_without_verdict(self, clean_routing, monkeypatch):
        # end-to-end: with parallel enabled but no table, the device
        # kernel must never be invoked
        def boom(*a, **k):
            raise AssertionError("device merkle dispatched without verdict")

        monkeypatch.setattr(tpu_merkle, "hash_from_byte_slices", boom)
        monkeypatch.setattr(cpu_merkle, "_parallel_enabled", True)
        items = [b"leaf %d" % i for i in range(300)]
        root = cpu_merkle.hash_from_byte_slices(items)
        assert len(root) == 32


class TestResidentCommitRouting:
    """verify_commit under the tpu backend reaches the resident path
    through the configured floor (BackendSpec), not an env re-read."""

    def _fixture(self, n=4):
        vals, privs = test_util.deterministic_validator_set(n, 10)
        bid = test_util.make_block_id()
        commit = test_util.make_commit(bid, 5, 0, vals, privs, CHAIN_ID)
        return vals, bid, commit

    def _spy(self, monkeypatch):
        calls = []
        real = ed25519_batch.verify_valset_resident

        def spy(vid, pks, msgs, sigs):
            calls.append(len(pks))
            return real(vid, pks, msgs, sigs)

        monkeypatch.setattr(ed25519_batch, "verify_valset_resident", spy)
        return calls

    def test_all_three_verify_commit_variants_route_resident(
        self, clean_routing, monkeypatch
    ):
        vals, bid, commit = self._fixture()
        calls = self._spy(monkeypatch)
        spec = BackendSpec("tpu", min_batch=1)
        vals.verify_commit(CHAIN_ID, bid, 5, commit, backend=spec)
        vals.verify_commit_light(CHAIN_ID, bid, 5, commit, backend=spec)
        vals.verify_commit_light_trusting(
            CHAIN_ID, commit, trust_level=Fraction(1, 3), backend=spec
        )
        assert len(calls) == 3

    def test_cpu_backend_never_touches_resident(
        self, clean_routing, monkeypatch
    ):
        vals, bid, commit = self._fixture()
        calls = self._spy(monkeypatch)
        vals.verify_commit(CHAIN_ID, bid, 5, commit, backend="cpu")
        assert calls == []

    def test_floor_gates_the_route(self, clean_routing, monkeypatch):
        vals, bid, commit = self._fixture()
        calls = self._spy(monkeypatch)
        spec = BackendSpec("tpu", min_batch=1000)  # 4 lanes < floor
        vals.verify_commit(CHAIN_ID, bid, 5, commit, backend=spec)
        assert calls == []

    def test_resident_verdict_matches_cpu_backend(self, clean_routing):
        vals, bid, commit = self._fixture(n=6)
        spec = BackendSpec("tpu", min_batch=1)
        # valid commit accepted by both
        vals.verify_commit(CHAIN_ID, bid, 5, commit, backend=spec)
        vals.verify_commit(CHAIN_ID, bid, 5, commit, backend="cpu")
        # corrupt one signature: both must reject
        bad = commit.signatures[2]
        bad_sig = bytes([bad.signature[0] ^ 1]) + bad.signature[1:]
        commit.signatures[2] = type(bad)(
            bad.block_id_flag, bad.validator_address, bad.timestamp, bad_sig
        )
        for backend in (spec, "cpu"):
            with pytest.raises(Exception):
                vals.verify_commit(CHAIN_ID, bid, 5, commit, backend=backend)


class TestConcurrentResident:
    def test_two_threads_race_the_cache_build(self, clean_routing):
        """Two threads verifying the same (uncached) valset must both
        return the correct mask and leave exactly ONE resident entry —
        the _get_resident adopt-the-race-winner contract."""
        from cometbft_tpu.crypto import ed25519 as ed

        keys = [
            ed.gen_priv_key_from_secret(b"race-%d" % i) for i in range(8)
        ]
        pks = [k.pub_key().bytes() for k in keys]
        msgs = [b"race vote %d" % i for i in range(8)]
        sigs = [k.sign(m) for k, m in zip(keys, msgs)]
        vid = hashlib.sha256(b"".join(pks)).digest()
        ed25519_batch._resident_cache.pop(vid, None)

        barrier = threading.Barrier(2)
        results, errors = [None, None], []

        def run(slot):
            try:
                barrier.wait(timeout=30)
                results[slot] = ed25519_batch.verify_valset_resident(
                    vid, pks, msgs, sigs
                )
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [
            threading.Thread(target=run, args=(i,), daemon=True)
            for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert results[0] == results[1] == [True] * 8
        assert vid in ed25519_batch._resident_cache

    def test_two_threads_verify_commit_concurrently(self, clean_routing):
        vals, privs = test_util.deterministic_validator_set(4, 10)
        bid = test_util.make_block_id()
        commit = test_util.make_commit(bid, 5, 0, vals, privs, CHAIN_ID)
        spec = BackendSpec("tpu", min_batch=1)
        barrier = threading.Barrier(2)
        errors = []

        def run():
            try:
                barrier.wait(timeout=30)
                vals.verify_commit(CHAIN_ID, bid, 5, commit, backend=spec)
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=run, daemon=True) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
