"""Per-device fault domains: topology registry, partial-mesh
degradation, and device-targeted chaos.

Contract under test (crypto/tpu/topology.py, crypto/supervisor.py,
crypto/scheduler.py, crypto/faults.py):
  - the DeviceTopology registry shards supervision state per fault
    domain; the legacy mesh.py module-global chunk-cap functions are a
    back-compat shim over the default topology's device 0;
  - a fault injected on ONE domain quarantines only that domain: the
    survivors keep serving the device path (no node-wide CPU fallback)
    with the batch axis redistributed over them, verdicts always equal
    to the CPU ground truth;
  - the quarantined domain is re-admitted by ITS OWN canary, on its own
    backoff schedule; only all-domains-BROKEN routes the node to CPU;
  - the scheduler's size-flush threshold scales to the healthy-domain
    capacity, and stop() during an in-flight quarantine/canary cannot
    deadlock;
  - per-device runtime state (OOM chunk-shrink) is reset on supervisor
    stop and on topology change — no incident state leaks into the
    next lifecycle.
"""

import threading
import time

import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.batch import BackendSpec, CPUBatchVerifier
from cometbft_tpu.crypto.faults import (
    FaultPlan,
    install,
    run_chaos_multidevice,
)
from cometbft_tpu.crypto.scheduler import VerifyScheduler
from cometbft_tpu.crypto.supervisor import (
    BROKEN,
    DEGRADED,
    HEALTHY,
    BackendSupervisor,
)
from cometbft_tpu.crypto.tpu import mesh, topology


def _make_items(n, tag=b"", poison_at=None):
    items = []
    for i in range(n):
        k = ed.gen_priv_key_from_secret(tag + bytes([i & 0xFF, i >> 8]))
        msg = b"fault-domain-msg-" + tag + i.to_bytes(4, "big")
        sig = k.sign(msg)
        if poison_at is not None and i == poison_at:
            sig = b"\x00" * 64
        items.append((k.pub_key(), msg, sig))
    return items


def _cpu_mask(items):
    bv = CPUBatchVerifier()
    for pk, m, s in items:
        bv.add(pk, m, s)
    _, mask = bv.verify()
    return mask


_seq = [0]


def _faulty_multi(n_domains, plan=None, **sup_kwargs):
    """A fresh FaultyBackend + supervisor sharded over an n-domain
    virtual topology (unique backend name per call)."""
    _seq[0] += 1
    name = f"test-domains-{_seq[0]}"
    plan = install(name=name, inner="cpu",
                   plan=plan if plan is not None else FaultPlan(seed=_seq[0]))
    topo = topology.DeviceTopology.virtual(n_domains)
    sup_kwargs.setdefault("dispatch_timeout_ms", 2000)
    sup_kwargs.setdefault("breaker_threshold", 1)
    sup_kwargs.setdefault("audit_pct", 0)
    sup_kwargs.setdefault("hedge_pct", 0)
    # push the async canary backoff past the test unless a test opts in
    sup_kwargs.setdefault("probe_base_ms", 60_000)
    sup_kwargs.setdefault("probe_max_ms", 120_000)
    sup = BackendSupervisor(spec=BackendSpec(name), topology=topo,
                            **sup_kwargs)
    return plan, sup, topo


@pytest.fixture(autouse=True)
def _restore_default_topology():
    """Tests that install a default topology must not leak it into the
    rest of the suite (the mesh shim and single-device supervisors
    resolve the process default)."""
    before = topology.default_topology()
    yield
    topology.set_default_topology(before)


class TestTopologyRegistry:
    def test_single_and_virtual_constructors(self):
        one = topology.DeviceTopology.single()
        assert len(one) == 1 and one.labels() == ["dev0"]
        four = topology.DeviceTopology.virtual(4)
        assert len(four) == 4
        assert four.labels() == ["dev0", "dev1", "dev2", "dev3"]
        assert [d.index for d in four] == [0, 1, 2, 3]
        with pytest.raises(ValueError):
            topology.DeviceTopology([])

    def test_per_device_shrink_ladder_is_independent(self):
        topo = topology.DeviceTopology.virtual(2)
        a, b = topo.device(0), topo.device(1)
        assert a.shrink_chunk_cap()
        assert a.chunk_shrink_levels() == 1
        assert b.chunk_shrink_levels() == 0  # untouched neighbor
        assert a.capacity_fraction() == 0.5
        assert b.capacity_fraction() == 1.0
        # hysteretic recovery on the shrunk device only
        assert not a.note_clean_dispatch(2)
        assert a.note_clean_dispatch(2)
        assert a.chunk_shrink_levels() == 0
        # floor: MAX_SHRINK_LEVELS halvings, then False
        for _ in range(mesh.MAX_SHRINK_LEVELS):
            assert b.shrink_chunk_cap()
        assert not b.shrink_chunk_cap()
        topo.reset_runtime_state()
        assert b.chunk_shrink_levels() == 0

    def test_mesh_globals_are_shim_over_default_device0(self):
        topo = topology.set_default_topology(
            topology.DeviceTopology.virtual(2)
        )
        assert mesh.chunk_shrink_levels() == 0
        assert mesh.shrink_chunk_cap()
        # the module-global view IS device 0's view
        assert topo.device(0).chunk_shrink_levels() == 1
        assert mesh.chunk_shrink_levels() == 1
        assert topo.device(1).chunk_shrink_levels() == 0
        # reset_chunk_shrink clears the WHOLE default topology
        topo.device(1).shrink_chunk_cap()
        mesh.reset_chunk_shrink()
        assert topo.device(0).chunk_shrink_levels() == 0
        assert topo.device(1).chunk_shrink_levels() == 0

    def test_device_scope_nests_and_is_thread_local(self):
        topo = topology.DeviceTopology.virtual(2)
        assert topology.current_device() is None
        with topology.device_scope(topo.device(0)) as d0:
            assert topology.current_device() is d0
            with topology.device_scope(topo.device(1)):
                assert topology.current_device() is topo.device(1)
            assert topology.current_device() is d0
            seen = []
            t = threading.Thread(
                target=lambda: seen.append(topology.current_device())
            )
            t.start()
            t.join()
            assert seen == [None]  # strictly thread-local
        assert topology.current_device() is None

    def test_fault_domains_default_resolution(self, monkeypatch):
        monkeypatch.delenv("CBFT_FAULT_DOMAINS", raising=False)
        assert topology.fault_domains_default() == 1
        assert topology.fault_domains_default(4) == 4
        assert topology.fault_domains_default(0) == 0  # 0 = auto-detect
        monkeypatch.setenv("CBFT_FAULT_DOMAINS", "8")
        assert topology.fault_domains_default(4) == 8  # env wins

    def test_set_default_topology_resets_old_and_new(self):
        old = topology.set_default_topology(
            topology.DeviceTopology.virtual(2)
        )
        old = topology.default_topology()
        old.device(0).shrink_chunk_cap()
        new = topology.DeviceTopology.virtual(3)
        new.device(1).shrink_chunk_cap()
        topology.set_default_topology(new)
        # a topology change is an incident boundary: both sides clean
        assert old.device(0).chunk_shrink_levels() == 0
        assert new.device(1).chunk_shrink_levels() == 0


class TestPartialMeshDegradation:
    def test_one_domain_quarantined_survivors_keep_device_path(self):
        plan, sup, topo = _faulty_multi(4, FaultPlan(seed=3, device=2))
        items = _make_items(4 * 32, poison_at=7)
        truth = _cpu_mask(items)

        # healthy: the batch shards over all 4 domains
        assert sup.verify_items(items) == truth
        assert sup.state() == HEALTHY
        assert all(plan.per_device.get(i, 0) >= 1 for i in range(4))

        # kill dev2: its shard fails, ONLY dev2 trips
        plan.exception_rate = 1.0
        assert sup.verify_items(items) == truth
        states = sup.device_states()
        assert states["dev2"] == BROKEN
        assert [k for k, v in states.items() if v == BROKEN] == ["dev2"]
        assert sup.state() == DEGRADED  # never node-wide BROKEN
        assert (
            sup.metrics.quarantines.with_labels(device="dev2").value() == 1
        )

        # while quarantined: survivors keep serving ON THE DEVICE PATH
        # with dev2's batch-axis share redistributed over them
        before = {i: plan.per_device.get(i, 0) for i in range(4)}
        cpu_before = sup.metrics.cpu_routed.value()
        redis_before = sup.metrics.redistributions.value()
        assert sup.verify_items(items) == truth
        assert sup.metrics.cpu_routed.value() == cpu_before
        assert sup.metrics.redistributions.value() == redis_before + 1
        after = {i: plan.per_device.get(i, 0) for i in range(4)}
        assert all(after[i] > before[i] for i in (0, 1, 3))
        assert after[2] == before[2]  # quarantined: no dispatches

        # re-admission by dev2's OWN canary once the fault clears
        plan.clear()
        assert sup.probe_now(device=2)
        assert sup.device_states()["dev2"] == HEALTHY
        assert sup.state() == HEALTHY
        assert (
            sup.metrics.readmissions.with_labels(device="dev2").value() == 1
        )
        assert sup.verify_items(items) == truth
        sup.stop()

    def test_breaker_state_gauge_tracks_exactly_one_device(self):
        plan, sup, topo = _faulty_multi(4, FaultPlan(seed=4, device=1))
        items = _make_items(4 * 32)
        plan.exception_rate = 1.0
        assert sup.verify_items(items) == [True] * len(items)
        gauge = sup.metrics.breaker_state
        per_dev = {
            d.handle.label: gauge.with_labels(
                device=d.handle.label
            ).value()
            for d in sup._domains
        }
        assert per_dev["dev1"] == 2.0  # BROKEN
        assert all(
            v == 0.0 for k, v in per_dev.items() if k != "dev1"
        )
        sup.stop()

    def test_all_domains_broken_routes_node_to_cpu(self):
        plan, sup, topo = _faulty_multi(2, FaultPlan(seed=5))  # no device
        items = _make_items(2 * 32, poison_at=5)
        truth = _cpu_mask(items)
        plan.exception_rate = 1.0
        assert sup.verify_items(items) == truth  # both shards fail → CPU
        assert sup.state() == BROKEN
        assert set(sup.device_states().values()) == {BROKEN}
        cpu_before = sup.metrics.cpu_routed.value()
        assert sup.verify_items(items) == truth
        assert sup.metrics.cpu_routed.value() == cpu_before + 1
        # a full-node probe re-admits every domain
        plan.clear()
        assert sup.probe_now()
        assert sup.state() == HEALTHY
        sup.stop()

    def test_small_batch_uses_fewer_domains(self):
        plan, sup, topo = _faulty_multi(4, FaultPlan(seed=6))
        # below 2 * _MIN_SHARD lanes there is nothing to shard: one
        # domain serves the whole batch (pad + per-shard overhead would
        # beat the parallelism)
        items = _make_items(16)
        assert sup.verify_items(items) == [True] * 16
        assert plan.per_device.get(0, 0) == 1
        assert all(plan.per_device.get(i, 0) == 0 for i in (1, 2, 3))
        sup.stop()

    def test_healthy_capacity_fraction(self):
        plan, sup, topo = _faulty_multi(4, FaultPlan(seed=7, device=0))
        assert sup.healthy_capacity_fraction() == 1.0
        plan.exception_rate = 1.0
        sup.verify_items(_make_items(4 * 32))
        # dev0 quarantined: 3 of 4 domains' capacity remains
        assert sup.healthy_capacity_fraction() == pytest.approx(0.75)
        # an OOM-shrunk survivor halves its own share
        topo.device(1).shrink_chunk_cap()
        assert sup.healthy_capacity_fraction() == pytest.approx(
            (0.5 + 1.0 + 1.0) / 4.0
        )
        sup.stop()


class TestSchedulerHealthyCapacity:
    class _FakeSup:
        def __init__(self, frac):
            self.frac = frac

        def healthy_capacity_fraction(self):
            if isinstance(self.frac, Exception):
                raise self.frac
            return self.frac

    def _sched(self, sup):
        return VerifyScheduler(
            spec=BackendSpec("cpu"), lane_budget=128, supervisor=sup
        )

    def test_budget_scales_to_healthy_fraction(self):
        assert self._sched(self._FakeSup(0.75))._effective_lane_budget() == 96
        assert self._sched(self._FakeSup(0.25))._effective_lane_budget() == 32

    def test_budget_nominal_when_healthy_absent_or_degenerate(self):
        assert self._sched(None)._effective_lane_budget() == 128
        assert self._sched(object())._effective_lane_budget() == 128
        assert self._sched(self._FakeSup(1.0))._effective_lane_budget() == 128
        # all-broken: dispatches CPU-route anyway; budget stays nominal
        assert self._sched(self._FakeSup(0.0))._effective_lane_budget() == 128
        assert (
            self._sched(
                self._FakeSup(RuntimeError("boom"))
            )._effective_lane_budget()
            == 128
        )

    def test_budget_floor_is_one_lane(self):
        s = VerifyScheduler(
            spec=BackendSpec("cpu"), lane_budget=2,
            supervisor=self._FakeSup(0.1),
        )
        assert s._effective_lane_budget() == 1


class TestStateLeakAndShutdown:
    def test_supervisor_stop_resets_per_device_shrink(self):
        # satellite 1: a restarted supervisor must not inherit a
        # shrunken chunk cap from a previous incident
        plan, sup, topo = _faulty_multi(2, FaultPlan(seed=8))
        topo.device(0).shrink_chunk_cap()
        topo.device(1).shrink_chunk_cap()
        topo.device(1).shrink_chunk_cap()
        sup.stop()
        assert topo.device(0).chunk_shrink_levels() == 0
        assert topo.device(1).chunk_shrink_levels() == 0

    def test_scheduler_stop_while_device_mid_canary(self):
        # satellite 2: stopping the scheduler while one quarantined
        # domain is mid-canary (probe thread wedged in a hanging
        # dispatch) must not deadlock — the join is timeout-bounded and
        # every pending future completes
        plan, sup, topo = _faulty_multi(
            2,
            FaultPlan(seed=9, device=1),
            dispatch_timeout_ms=300,
            probe_base_ms=1,  # canary due immediately after the trip
            probe_max_ms=10,
        )
        sched = VerifyScheduler(
            spec=BackendSpec("cpu"), flush_us=100, supervisor=sup,
            join_timeout_s=5.0,
        )
        sched.start()
        items = _make_items(2 * 32)
        # trip dev1 (its shard hangs; the watchdog abandons it), then
        # submit again so _maybe_probe_async launches dev1's canary into
        # the still-armed hang: the probe thread is now mid-canary
        plan.hang_rate = 1.0
        plan.hang_s = 20.0
        fut = sched.submit(items)
        assert fut.result(timeout=30.0)[1] == [True] * len(items)
        assert sup.device_states()["dev1"] == BROKEN
        time.sleep(0.05)
        fut2 = sched.submit(items)
        assert fut2.result(timeout=30.0)[1] == [True] * len(items)
        t0 = time.perf_counter()
        sched.stop()
        stopped_in = time.perf_counter() - t0
        assert stopped_in < 10.0, f"scheduler stop took {stopped_in:.1f}s"
        plan.clear()
        t0 = time.perf_counter()
        sup.stop()  # joins the canary thread, bounded by the watchdog
        assert time.perf_counter() - t0 < 10.0
        assert fut.done() and fut2.done()


class TestMultiDeviceChaosRung:
    def test_chaos_multidevice_acceptance(self):
        # the PR's acceptance rung: >= 4 virtual domains, device 2
        # injected with hang → oom → corrupt; survivors keep serving the
        # device path, dev2 is quarantined and re-admitted by its own
        # canary, zero wrong verdicts, exactly one domain leaves HEALTHY
        summary = run_chaos_multidevice(devices=4, kill=2, seed=7)
        assert summary["wrong_verdicts"] == 0
        assert summary["cpu_routed"] == 0
        assert set(summary["quarantines"]) == {"dev2"}
        assert summary["readmissions"]["dev2"] >= 3
        assert summary["redistributions"] >= 3
        for phase in ("hang", "oom", "corrupt"):
            p = summary["phases"][phase]
            assert p["quarantined_only_kill"], phase
            assert p["survivors_grew"], phase
            assert (
                p["state_while_quarantined"]
                == summary["expected"]["state_while_quarantined"]
            ), phase
            assert p["readmit_probe_ok"], phase
        assert all(
            s == summary["expected"]["final_state"]
            for s in summary["final_states"].values()
        )
        # survivors dispatched in every phase; dev2 only while healthy
        per_dev = summary["per_device_dispatches"]
        assert all(per_dev.get(i, 0) >= 3 for i in (0, 1, 3))
