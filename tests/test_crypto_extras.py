"""Crypto extras: XChaCha20-Poly1305, XSalsa20 secretbox, ASCII armor,
and sr25519 schnorrkel signatures.

Model: reference crypto/{xchacha20poly1305,xsalsa20symmetric,armor,
sr25519} test files. HChaCha20 is cross-validated against the audited
`cryptography` library's ChaCha20 (the rounds output is recoverable from
a keystream block by subtracting the initial state).
"""

import struct

import pytest

from cometbft_tpu.crypto import armor, sr25519, xsalsa20symmetric as xsalsa
from cometbft_tpu.crypto.xchacha20poly1305 import (
    XChaCha20Poly1305,
    hchacha20,
)

try:  # slim image: modules under test raise the purepy mirrors instead
    from cryptography.exceptions import InvalidSignature, InvalidTag
except ImportError:
    from cometbft_tpu.crypto.purepy import InvalidSignature, InvalidTag


class TestXChaCha20Poly1305:
    def test_hchacha20_matches_library_chacha20(self):
        """Derive the expected HChaCha20 output from an independent
        ChaCha20: keystream block = rounds(state) + state, so
        rounds-output words = block words - initial words."""
        key = bytes(range(32))
        nonce16 = bytes(range(16, 32))
        # ChaCha20 nonce layout = 4-byte counter ‖ 12-byte nonce;
        # HChaCha's state puts nonce16[0:4] in the counter slot
        try:
            from cryptography.hazmat.primitives.ciphers import (
                Cipher,
                algorithms,
            )

            algo = algorithms.ChaCha20(key, nonce16)
            ks = Cipher(algo, mode=None).encryptor().update(b"\x00" * 64)
        except ImportError:  # purepy's block fn is a second implementation
            from cometbft_tpu.crypto.purepy import _chacha_block

            ks = _chacha_block(
                struct.unpack("<8I", key),
                struct.unpack("<I", nonce16[:4])[0],
                struct.unpack("<3I", nonce16[4:]),
            )
        block = struct.unpack("<16I", ks)
        sigma = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
        init = (
            list(sigma)
            + list(struct.unpack("<8I", key))
            + list(struct.unpack("<4I", nonce16))
        )
        rounds_out = [(b - i) & 0xFFFFFFFF for b, i in zip(block, init)]
        want = struct.pack("<8I", *(rounds_out[0:4] + rounds_out[12:16]))
        assert hchacha20(key, nonce16) == want

    def test_seal_open_roundtrip_and_forgery(self):
        key = bytes(range(32))
        aead = XChaCha20Poly1305(key)
        nonce = bytes(range(24))
        ct = aead.encrypt(nonce, b"secret payload", b"header")
        assert aead.decrypt(nonce, ct, b"header") == b"secret payload"
        with pytest.raises(InvalidTag):
            aead.decrypt(nonce, ct[:-1] + bytes([ct[-1] ^ 1]), b"header")
        with pytest.raises(InvalidTag):
            aead.decrypt(nonce, ct, b"wrong aad")
        # different nonces → different ciphertexts
        assert aead.encrypt(bytes(24), b"x") != aead.encrypt(
            b"\x01" + bytes(23), b"x"
        )

    def test_bad_lengths(self):
        with pytest.raises(ValueError):
            XChaCha20Poly1305(b"short")
        with pytest.raises(ValueError):
            XChaCha20Poly1305(bytes(32)).encrypt(b"short-nonce", b"x")


class TestXSalsa20Symmetric:
    def test_encrypt_decrypt_roundtrip(self):
        secret = bytes(range(32))
        for pt in (b"x", b"the quick brown fox" * 20):
            ct = xsalsa.encrypt_symmetric(pt, secret)
            assert len(ct) == xsalsa.NONCE_LEN + xsalsa.OVERHEAD + len(pt)
            assert xsalsa.decrypt_symmetric(ct, secret) == pt
        # empty plaintext is rejected on decrypt, like the reference's
        # length guard (symmetric.go:41)
        with pytest.raises(ValueError):
            xsalsa.decrypt_symmetric(
                xsalsa.encrypt_symmetric(b"", secret), secret
            )

    def test_tamper_detection(self):
        secret = bytes(range(32))
        ct = bytearray(xsalsa.encrypt_symmetric(b"payload", secret))
        ct[-1] ^= 1
        with pytest.raises(InvalidSignature):
            xsalsa.decrypt_symmetric(bytes(ct), secret)

    def test_wrong_secret_rejected(self):
        ct = xsalsa.encrypt_symmetric(b"payload", bytes(32))
        with pytest.raises(InvalidSignature):
            xsalsa.decrypt_symmetric(ct, b"\x01" * 32)

    def test_secret_length_enforced(self):
        with pytest.raises(ValueError):
            xsalsa.encrypt_symmetric(b"x", b"short")

    def test_nacl_known_answer(self):
        """The canonical crypto_secretbox vector (NaCl tests/box.c — the
        same key/nonce/message triple the reference's
        golang.org/x/crypto/nacl/secretbox interops with). Passing MAC
        verification here pins byte-level NaCl compatibility: the Poly1305
        key and the keystream placement must both be exact."""
        key = bytes.fromhex(
            "1b27556473e985d462cd51197a9a46c7"
            "6009549eac6474f206c4ee0844f68389"
        )
        nonce = bytes.fromhex(
            "69696ee955b62b73cd62bda875fc73d68219e0036b7a0b37"
        )
        ct = bytes.fromhex(
            "f3ffc7703f9400e52a7dfb4b3d3305d9"
            "8e993b9f48681273c29650ba32fc76ce"
            "48332ea7164d96a4476fb8c531a1186a"
            "c0dfc17c98dce87b4da7f011ec48c972"
            "71d2c20f9b928fe2270d6fb863d51738"
            "b48eeee314a7cc8ab932164548e526ae"
            "90224368517acfeabd6bb3732bc0e9da"
            "99832b61ca01b6de56244a9e88d5f9b3"
            "7973f622a43d14a6599b1f654cb45a74"
            "e355a5"
        )
        pt = xsalsa.open_(ct, nonce, key)
        assert len(pt) == 131
        assert pt.startswith(bytes.fromhex("be075fc53c81f2d5cf141316ebeb0c7b"))
        assert xsalsa.seal(pt, nonce, key) == ct


class TestArmor:
    def test_roundtrip(self):
        data = bytes(range(200))
        s = armor.encode_armor("TEST BLOCK", {"version": "1"}, data)
        block_type, headers, out = armor.decode_armor(s)
        assert block_type == "TEST BLOCK"
        assert headers == {"version": "1"}
        assert out == data

    def test_checksum_detects_corruption(self):
        s = armor.encode_armor("T", {}, b"hello armor world")
        lines = s.splitlines()
        # corrupt one base64 body char
        for i, ln in enumerate(lines):
            if ln and not ln.startswith("-") and ":" not in ln and not ln.startswith("="):
                lines[i] = ("A" if ln[0] != "A" else "B") + ln[1:]
                break
        with pytest.raises(ValueError):
            armor.decode_armor("\n".join(lines))

    def test_armored_privkey_roundtrip(self):
        key = bytes(range(32, 64))
        s = armor.encrypt_armor_priv_key(key, "hunter2")
        assert "BEGIN TENDERMINT PRIVATE KEY" in s
        assert "kdf: scrypt" in s
        assert armor.unarmor_decrypt_priv_key(s, "hunter2") == key
        with pytest.raises(InvalidSignature):
            armor.unarmor_decrypt_priv_key(s, "wrong-pass")

    def test_legacy_and_foreign_kdfs_rejected(self):
        """Pre-NaCl-fix 'sha256-salt' blobs would MAC-verify but decrypt
        to garbage under the fixed keystream — they must be refused, not
        silently corrupted; the reference's 'bcrypt' header is likewise
        not interoperable."""
        blob = armor.encode_armor(
            armor.PRIVKEY_BLOCK_TYPE,
            {"kdf": "sha256-salt", "salt": "00" * 16},
            b"whatever",
        )
        with pytest.raises(ValueError, match="pre-NaCl-fix"):
            armor.unarmor_decrypt_priv_key(blob, "pw")
        blob = armor.encode_armor(
            armor.PRIVKEY_BLOCK_TYPE,
            {"kdf": "bcrypt", "salt": "00" * 16},
            b"whatever",
        )
        with pytest.raises(ValueError, match="unrecognized KDF"):
            armor.unarmor_decrypt_priv_key(blob, "pw")

    def test_malformed(self):
        with pytest.raises(ValueError):
            armor.decode_armor("not armor at all")


class TestSr25519:
    def test_sign_verify(self):
        k = sr25519.gen_priv_key_from_secret(b"validator-1")
        pk = k.pub_key()
        msg = b"vote sign bytes"
        sig = k.sign(msg)
        assert len(sig) == sr25519.SIGNATURE_SIZE
        assert pk.verify_signature(msg, sig)
        assert not pk.verify_signature(b"other message", sig)

    def test_corrupted_signature_rejected(self):
        k = sr25519.gen_priv_key_from_secret(b"v")
        sig = bytearray(k.sign(b"m"))
        for pos in (0, 31, 33, 62):
            bad = bytearray(sig)
            bad[pos] ^= 1
            assert not k.pub_key().verify_signature(b"m", bytes(bad))

    def test_format_marker_required(self):
        """schnorrkel 'new' format: the s high bit must be set."""
        k = sr25519.gen_priv_key_from_secret(b"v")
        sig = bytearray(k.sign(b"m"))
        sig[63] &= 0x7F
        assert not k.pub_key().verify_signature(b"m", bytes(sig))

    def test_wrong_key_rejected(self):
        k1 = sr25519.gen_priv_key_from_secret(b"a")
        k2 = sr25519.gen_priv_key_from_secret(b"b")
        sig = k1.sign(b"m")
        assert not k2.pub_key().verify_signature(b"m", sig)

    def test_ristretto_roundtrip_and_invalid_encodings(self):
        k = sr25519.gen_priv_key_from_secret(b"r")
        pk = k.pub_key().bytes()
        pt = sr25519._decode(pk)
        assert pt is not None
        assert sr25519._encode(pt) == pk
        # non-canonical (>= p) and negative encodings rejected
        assert sr25519._decode(b"\xff" * 32) is None
        assert sr25519._decode(b"\x01" + b"\x00" * 31) is None  # odd = negative
        # identity encodes to all zeros and decodes
        assert sr25519._encode(sr25519._ID) == bytes(32)

    def test_address_and_type(self):
        k = sr25519.gen_priv_key_from_secret(b"t")
        assert len(k.pub_key().address()) == 20
        assert k.pub_key().type() == "sr25519"
        assert k.type() == "sr25519"

    def test_amino_tag(self):
        from cometbft_tpu.libs import amino_json

        k = sr25519.gen_priv_key_from_secret(b"amino")
        s = amino_json.marshal(k.pub_key())
        assert "tendermint/PubKeySr25519" in s
        back = amino_json.unmarshal(s)
        assert back.bytes() == k.pub_key().bytes()
