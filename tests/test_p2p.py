"""P2P stack tests: merlin transcript, SecretConnection handshake/IO,
MConnection multiplexing + priorities, transport upgrade, switch lifecycle
over real TCP sockets.

Model: reference p2p/conn/secret_connection_test.go, connection_test.go,
switch_test.go.
"""

import queue
import socket
import threading
import time

import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.merlin import Strobe128, Transcript
from cometbft_tpu.p2p import (
    ChannelDescriptor,
    MConnConfig,
    MConnection,
    MultiplexTransport,
    NetAddress,
    NodeInfo,
    NodeKey,
    ProtocolVersion,
    Reactor,
    RejectedError,
    SecretConnection,
    Switch,
    pub_key_to_id,
)
from cometbft_tpu.p2p.conn.connection import (
    PacketMsg,
    SocketStream,
    unwrap_packet,
    wrap_packet_msg,
    wrap_packet_ping,
    wrap_packet_pong,
)


# -- merlin ------------------------------------------------------------------


class TestMerlin:
    def test_published_vector(self):
        # merlin's own conformance test (equivalence_simple)
        t = Transcript(b"test protocol")
        t.append_message(b"some label", b"some data")
        c = t.challenge_bytes(b"challenge", 32)
        assert (
            c.hex()
            == "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"
        )

    def test_deterministic(self):
        def run():
            t = Transcript(b"proto")
            t.append_message(b"a", b"b" * 100)
            return t.challenge_bytes(b"c", 64)

        assert run() == run()

    def test_order_matters(self):
        t1 = Transcript(b"p")
        t1.append_message(b"x", b"1")
        t1.append_message(b"y", b"2")
        t2 = Transcript(b"p")
        t2.append_message(b"y", b"2")
        t2.append_message(b"x", b"1")
        assert t1.challenge_bytes(b"c", 32) != t2.challenge_bytes(b"c", 32)


# -- secret connection -------------------------------------------------------


def _make_secret_pair(key_a=None, key_b=None):
    a, b = socket.socketpair()
    ka = key_a or ed.gen_priv_key()
    kb = key_b or ed.gen_priv_key()
    out = {}
    errs = {}

    def side(name, sock, key):
        try:
            out[name] = SecretConnection.make(sock, key)
        except Exception as exc:  # noqa: BLE001
            errs[name] = exc

    t1 = threading.Thread(target=side, args=("a", a, ka))
    t2 = threading.Thread(target=side, args=("b", b, kb))
    t1.start()
    t2.start()
    t1.join(10)
    t2.join(10)
    if errs:
        raise RuntimeError(errs)
    return out["a"], out["b"], ka, kb


class TestSecretConnection:
    def test_handshake_authenticates_both_sides(self):
        sca, scb, ka, kb = _make_secret_pair()
        assert sca.rem_pub_key.bytes() == kb.pub_key().bytes()
        assert scb.rem_pub_key.bytes() == ka.pub_key().bytes()

    def test_roundtrip_small_and_large(self):
        sca, scb, _, _ = _make_secret_pair()
        sca.write(b"ping")
        assert scb.read_exact(4) == b"ping"
        big = bytes(range(256)) * 40  # > 1 frame
        scb.write(big)
        assert sca.read_exact(len(big)) == big

    def test_tampered_frame_rejected(self):
        a, b = socket.socketpair()
        ka, kb = ed.gen_priv_key(), ed.gen_priv_key()
        out = {}

        def side(name, sock, key):
            out[name] = SecretConnection.make(sock, key)

        t1 = threading.Thread(target=side, args=("a", a, ka))
        t2 = threading.Thread(target=side, args=("b", b, kb))
        t1.start(), t2.start(), t1.join(10), t2.join(10)
        sca, scb = out["a"], out["b"]
        # write a tampered sealed frame directly to the raw socket
        sca.write(b"x")  # advance nonce legitimately once
        scb.read_exact(1)
        a.sendall(b"\x00" * (1028 + 16))
        with pytest.raises(Exception):
            scb.read_exact(1)


# -- packets -----------------------------------------------------------------


class TestPackets:
    def test_packet_msg_roundtrip(self):
        pm = PacketMsg(0x22, True, b"payload")
        kind, got = unwrap_packet(wrap_packet_msg(pm))
        assert kind == "msg"
        assert got == pm

    def test_channel_zero_roundtrip(self):
        pm = PacketMsg(0x00, False, b"pex")
        kind, got = unwrap_packet(wrap_packet_msg(pm))
        assert got.channel_id == 0 and got.data == b"pex"

    def test_ping_pong(self):
        assert unwrap_packet(wrap_packet_ping())[0] == "ping"
        assert unwrap_packet(wrap_packet_pong())[0] == "pong"


# -- mconnection -------------------------------------------------------------


def _mconn_pair(descs, on_recv_b, config=None):
    a, b = socket.socketpair()
    errs = []
    m1 = MConnection(
        SocketStream(a), descs, lambda ch, m: None, errs.append, config=config
    )
    m2 = MConnection(SocketStream(b), descs, on_recv_b, errs.append, config=config)
    m1.start()
    m2.start()
    return m1, m2, errs


class TestMConnection:
    def test_send_receive_multiplexed(self):
        got = queue.Queue()
        descs = [
            ChannelDescriptor(id=0x01, priority=5),
            ChannelDescriptor(id=0x02, priority=1),
        ]
        m1, m2, errs = _mconn_pair(descs, lambda ch, m: got.put((ch, m)))
        try:
            assert m1.send(0x01, b"one")
            assert m1.send(0x02, b"B" * 4000)
            msgs = {got.get(timeout=5)[0]: 1, got.get(timeout=5)[0]: 1}
            assert set(msgs) == {0x01, 0x02}
            assert not errs
        finally:
            _safe_stop(m1)
            _safe_stop(m2)

    def test_send_to_unknown_channel_fails(self):
        descs = [ChannelDescriptor(id=0x01)]
        m1, m2, _ = _mconn_pair(descs, lambda ch, m: None)
        try:
            assert not m1.send(0x99, b"nope")
        finally:
            _safe_stop(m1)
            _safe_stop(m2)

    def test_large_message_reassembled(self):
        got = queue.Queue()
        descs = [ChannelDescriptor(id=0x01, priority=1)]
        m1, m2, errs = _mconn_pair(descs, lambda ch, m: got.put(m))
        try:
            big = bytes(i % 251 for i in range(100_000))
            assert m1.send(0x01, big)
            assert got.get(timeout=10) == big
            assert not errs
        finally:
            _safe_stop(m1)
            _safe_stop(m2)

    def test_ping_pong_keepalive(self):
        got = queue.Queue()
        descs = [ChannelDescriptor(id=0x01)]
        cfg = MConnConfig(ping_interval=0.2, pong_timeout=2.0)
        m1, m2, errs = _mconn_pair(descs, lambda ch, m: got.put(m), config=cfg)
        try:
            time.sleep(0.8)  # several ping rounds
            assert not errs  # pongs arrived; no pong-timeout errors
            assert m1.is_running() and m2.is_running()
        finally:
            _safe_stop(m1)
            _safe_stop(m2)


# -- transport + switch ------------------------------------------------------


def _node(network="test-chain", channels=bytes([0x01, 0x02])):
    nk = NodeKey(ed.gen_priv_key())
    info = NodeInfo(
        protocol_version=ProtocolVersion(),
        node_id=nk.id(),
        listen_addr="127.0.0.1:0",
        network=network,
        channels=channels,
        moniker="test",
    )
    return nk, info


def _make_transport(network="test-chain", channels=bytes([0x01, 0x02])):
    nk, info = _node(network, channels)
    t = MultiplexTransport(info, nk)
    t.listen(NetAddress("", "127.0.0.1", 0))
    # advertise the bound port
    info.listen_addr = f"127.0.0.1:{t.listen_addr.port}"
    return t


class TestTransport:
    def test_dial_accept_upgrade(self):
        t1 = _make_transport()
        t2 = _make_transport()
        result = {}

        def accept():
            result["up"] = t1.accept()

        th = threading.Thread(target=accept)
        th.start()
        up2 = t2.dial(t1.listen_addr)
        th.join(10)
        up1 = result["up"]
        assert up1.node_info.id() == t2.node_info.id()
        assert up2.node_info.id() == t1.node_info.id()
        assert up2.outbound and not up1.outbound
        t1.close()
        t2.close()

    def test_dialed_id_mismatch_rejected(self):
        t1 = _make_transport()
        t2 = _make_transport()
        threading.Thread(target=lambda: _try(t1.accept), daemon=True).start()
        wrong_id = NodeKey(ed.gen_priv_key()).id()
        bad = NetAddress(wrong_id, t1.listen_addr.ip, t1.listen_addr.port)
        with pytest.raises(RejectedError, match="mismatch"):
            t2.dial(bad)
        t1.close()
        t2.close()

    def test_network_mismatch_rejected(self):
        t1 = _make_transport(network="chain-A")
        t2 = _make_transport(network="chain-B")
        threading.Thread(target=lambda: _try(t1.accept), daemon=True).start()
        with pytest.raises(RejectedError, match="different network"):
            t2.dial(t1.listen_addr)
        t1.close()
        t2.close()


def _try(fn):
    try:
        fn()
    except Exception:
        pass


class EchoReactor(Reactor):
    """Test reactor: records receives; echoes on the other channel."""

    def __init__(self, ch_ids, priority=1):
        super().__init__("echo")
        self.ch_ids = ch_ids
        self.priority = priority
        self.received = queue.Queue()
        self.peers_added = []
        self.peers_removed = []

    def get_channels(self):
        return [ChannelDescriptor(id=c, priority=self.priority) for c in self.ch_ids]

    def add_peer(self, peer):
        self.peers_added.append(peer.id())

    def remove_peer(self, peer, reason):
        self.peers_removed.append(peer.id())

    def receive(self, ch_id, peer, msg_bytes):
        self.received.put((ch_id, peer.id(), msg_bytes))


def _make_switch(network="test-chain", chs=(0x01, 0x02)):
    t = _make_transport(network, bytes(chs))
    sw = Switch(t, reconnect_interval=0.1)
    r = EchoReactor(list(chs))
    sw.add_reactor("echo", r)
    return sw, r


class TestSwitch:
    def test_two_switches_connect_and_exchange(self):
        sw1, r1 = _make_switch()
        sw2, r2 = _make_switch()
        sw1.start()
        sw2.start()
        try:
            sw2.dial_peer_with_address(sw1.transport.listen_addr)
            _wait(lambda: sw1.peers.size() == 1 and sw2.peers.size() == 1)
            # both reactors saw the peer (add_peer fires just after peer add)
            _wait(lambda: r1.peers_added and r2.peers_added)
            # exchange on both channels
            p21 = sw2.peers.list()[0]
            assert p21.send(0x01, b"hello-1")
            assert p21.send(0x02, b"hello-2")
            got = {r1.received.get(timeout=5)[0], r1.received.get(timeout=5)[0]}
            assert got == {0x01, 0x02}
        finally:
            sw1.stop()
            sw2.stop()

    def test_broadcast_reaches_all_peers(self):
        hub, rhub = _make_switch()
        spokes = [_make_switch() for _ in range(3)]
        hub.start()
        for sw, _ in spokes:
            sw.start()
            sw.dial_peer_with_address(hub.transport.listen_addr)
        try:
            _wait(lambda: hub.peers.size() == 3)
            hub.broadcast(0x01, b"fan-out")
            for _, r in spokes:
                ch, _, msg = r.received.get(timeout=5)
                assert (ch, msg) == (0x01, b"fan-out")
        finally:
            hub.stop()
            for sw, _ in spokes:
                sw.stop()

    def test_stop_peer_for_error_removes_and_notifies(self):
        sw1, r1 = _make_switch()
        sw2, r2 = _make_switch()
        sw1.start()
        sw2.start()
        try:
            sw2.dial_peer_with_address(sw1.transport.listen_addr)
            _wait(lambda: sw1.peers.size() == 1)
            peer = sw1.peers.list()[0]
            sw1.stop_peer_for_error(peer, ValueError("test error"))
            _wait(lambda: sw1.peers.size() == 0)
            assert r1.peers_removed == [peer.id()]
        finally:
            sw1.stop()
            sw2.stop()

    def test_peer_disconnect_detected_and_removed(self):
        sw1, r1 = _make_switch()
        sw2, r2 = _make_switch()
        sw1.start()
        sw2.start()
        try:
            sw2.dial_peer_with_address(sw1.transport.listen_addr)
            _wait(lambda: sw1.peers.size() == 1 and sw2.peers.size() == 1)
            sw2.stop()  # closes connections
            _wait(lambda: sw1.peers.size() == 0, timeout=10)
        finally:
            sw1.stop()

    def test_duplicate_dial_rejected(self):
        sw1, _ = _make_switch()
        sw2, _ = _make_switch()
        sw1.start()
        sw2.start()
        try:
            sw2.dial_peer_with_address(sw1.transport.listen_addr)
            _wait(lambda: sw2.peers.size() == 1)
            with pytest.raises(RejectedError):
                sw2.dial_peer_with_address(sw1.transport.listen_addr)
        finally:
            sw1.stop()
            sw2.stop()

    def test_persistent_peer_reconnects(self):
        sw1, _ = _make_switch()
        sw2, _ = _make_switch()
        sw1.start()
        sw2.start()
        try:
            addr = sw1.transport.listen_addr
            sw2.add_persistent_peers([str(addr)])
            sw2.dial_peers_async([addr])
            _wait(lambda: sw2.peers.size() == 1)
            # kill from sw1 side; sw2 should re-dial
            peer = sw1.peers.list()[0]
            sw1.stop_peer_for_error(peer, RuntimeError("boom"))
            _wait(lambda: sw2.peers.size() == 0, timeout=10)
            _wait(lambda: sw2.peers.size() == 1, timeout=10)
        finally:
            sw1.stop()
            sw2.stop()

    def test_persistent_peer_reconnects_after_quick_window(self):
        """An outage longer than the quick reconnect window must still
        heal via the exponential backoff phase (reference: the second
        loop of p2p/switch.go reconnectToPeer) — this is the partition
        case, where every quick attempt fails before the link returns."""
        nk, info = _node()
        t1 = MultiplexTransport(info, nk)
        t1.listen(NetAddress("", "127.0.0.1", 0))
        port = t1.listen_addr.port
        info.listen_addr = f"127.0.0.1:{port}"
        sw1 = Switch(t1, reconnect_interval=0.1)
        sw1.add_reactor("echo", EchoReactor([0x01, 0x02]))
        sw2, _ = _make_switch()
        sw1.start()
        sw2.start()
        sw1b = None
        try:
            addr = sw1.transport.listen_addr
            sw2.add_persistent_peers([str(addr)])
            sw2.dial_peers_async([addr])
            _wait(lambda: sw2.peers.size() == 1)
            sw1.stop()  # outage: listener gone, every dial fails
            _wait(lambda: sw2.peers.size() == 0, timeout=10)
            # outlast the quick window (20 x 0.1s x 1.2 jitter + dial
            # overhead < 4s) so only the backoff phase can heal this;
            # then PROVE the quick phase is spent before resurrecting
            time.sleep(5.0)
            assert sw2.peers.size() == 0, "reconnected with no listener?"
            # resurrect the peer on the SAME identity and port
            t1b = MultiplexTransport(info, nk)
            t1b.listen(NetAddress("", "127.0.0.1", port))
            sw1b = Switch(t1b, reconnect_interval=0.1)
            sw1b.add_reactor("echo", EchoReactor([0x01, 0x02]))
            sw1b.start()
            _wait(lambda: sw2.peers.size() == 1, timeout=25, interval=0.1)
        finally:
            _safe_stop(sw1)
            if sw1b is not None:
                _safe_stop(sw1b)
            _safe_stop(sw2)


def _safe_stop(svc):
    """Stop tolerating the race where the error path already stopped it."""
    try:
        svc.stop()
    except Exception:
        pass


def _wait(cond, timeout=8.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError("condition not met before timeout")


class TestNodeKey:
    def test_id_is_hex_address(self):
        nk = NodeKey(ed.gen_priv_key())
        assert nk.id() == nk.pub_key().address().hex()
        assert len(nk.id()) == 40

    def test_save_load_roundtrip(self, tmp_path):
        p = str(tmp_path / "node_key.json")
        nk = NodeKey.load_or_gen(p)
        nk2 = NodeKey.load_or_gen(p)
        assert nk.id() == nk2.id()


class TestNetAddress:
    def test_parse_roundtrip(self):
        nid = "aa" * 20
        na = NetAddress.from_string(f"{nid}@127.0.0.1:26656")
        assert na.id == nid and na.ip == "127.0.0.1" and na.port == 26656
        assert str(na) == f"{nid}@127.0.0.1:26656"

    def test_missing_id_rejected(self):
        with pytest.raises(ValueError):
            NetAddress.from_string("127.0.0.1:26656")

    def test_proto_roundtrip(self):
        na = NetAddress("bb" * 20, "10.0.0.1", 1234)
        assert NetAddress.decode(na.encode()) == na

    def test_routable(self):
        assert not NetAddress("", "127.0.0.1", 80).routable()
        assert not NetAddress("", "192.168.1.1", 80).routable()
        assert NetAddress("", "8.8.8.8", 80).routable()


class TestUnconditionalPeers:
    def test_exempt_from_inbound_cap(self):
        """p2p.unconditional_peer_ids: listed peers connect past the
        inbound limit even when not persistent (reference switch.go —
        the knob was previously inert; only persistent peers were
        exempt)."""
        t1 = _make_transport()
        sw1 = Switch(t1, max_inbound_peers=0)  # zero cap: everyone refused
        sw1.add_reactor("echo", EchoReactor([0x01, 0x02]))
        sw2, _ = _make_switch()
        sw1.start()
        sw2.start()
        try:
            addr = sw1.transport.listen_addr
            # over-cap and not listed: the inbound side never admits it
            try:
                sw2.dial_peer_with_address(addr)
            except Exception:
                pass
            time.sleep(0.5)
            assert sw1.peers.size() == 0
            # listed as unconditional: admitted despite the zero cap.
            # Retry inside the wait — the first refused dial may linger
            # briefly on sw2's side as a dead duplicate
            sw1.unconditional_peer_ids.add(sw2.transport.node_key.id())
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and sw1.peers.size() != 1:
                try:
                    sw2.dial_peer_with_address(addr)
                except Exception:
                    pass
                time.sleep(0.2)
            assert sw1.peers.size() == 1
        finally:
            _safe_stop(sw1)
            _safe_stop(sw2)


class TestPeerFilters:
    def test_peer_filter_rejects_by_id(self):
        """Switch-level PeerFilterFunc (reference createTransport peer
        filters): a filter raising for the peer's ID rejects it after
        the handshake, before admission."""
        sw1, _ = _make_switch()
        sw2, _ = _make_switch()
        banned = sw2.transport.node_key.id()

        def id_filter(peer_id: str) -> None:
            if peer_id == banned:
                raise ValueError("filtered by app")

        sw1.peer_filters.append(id_filter)
        sw1.start()
        sw2.start()
        try:
            # the filter runs on the ACCEPTOR: the dialer's side may
            # briefly hold the conn, but sw1 never admits the peer
            try:
                sw2.dial_peer_with_address(sw1.transport.listen_addr)
            except Exception:
                pass
            time.sleep(0.5)
            assert sw1.peers.size() == 0
            # a different peer passes the same filter
            sw3, _ = _make_switch()
            sw3.start()
            try:
                sw3.dial_peer_with_address(sw1.transport.listen_addr)
                _wait(lambda: sw1.peers.size() == 1)
            finally:
                _safe_stop(sw3)
        finally:
            _safe_stop(sw1)
            _safe_stop(sw2)
