"""BackendSupervisor: circuit breaker, dispatch watchdog, corruption
audit, fault injection, and the scheduler robustness satellites.

Contract under test (crypto/supervisor.py, crypto/faults.py,
crypto/scheduler.py, crypto/tpu/mesh.py):
  - verdicts ALWAYS match the CPU ground truth, under every injected
    failure mode (exceptions, hangs, silent corruption, sudden death,
    jitter);
  - the breaker walks HEALTHY → DEGRADED → BROKEN exactly as specced
    and canary probes re-admit the backend after it recovers;
  - a wedged dispatch is abandoned within dispatch_timeout_ms and the
    zombie thread exits early through the mesh cancel event;
  - submit() is bounded by [crypto] max_queue and degrades to inline
    CPU verification when the deadline expires — no future lost;
  - stop() detects a failed worker join and fails pending futures
    instead of leaving callers blocked.
"""

import threading
import time

import pytest

from cometbft_tpu.crypto import batch as cryptobatch
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.batch import (
    BackendSpec,
    CPUBatchVerifier,
    new_batch_verifier,
    unwrap_backend,
)
from cometbft_tpu.crypto.faults import (
    FaultInjected,
    FaultPlan,
    FaultyBackend,
    install,
    run_chaos_soak,
)
from cometbft_tpu.crypto.scheduler import VerifyScheduler
from cometbft_tpu.crypto.supervisor import (
    BROKEN,
    DEGRADED,
    HEALTHY,
    BackendSupervisor,
    SupervisedBatchVerifier,
    WatchdogTimeout,
    audit_pct_default,
    breaker_threshold_default,
    dispatch_timeout_ms_default,
)


def _make_items(n, tag=b"", poison_at=None):
    items = []
    for i in range(n):
        k = ed.gen_priv_key_from_secret(tag + bytes([i & 0xFF, i >> 8]))
        msg = b"supervisor-msg-" + tag + i.to_bytes(4, "big")
        sig = k.sign(msg)
        if poison_at is not None and i == poison_at:
            sig = b"\x00" * 64
        items.append((k.pub_key(), msg, sig))
    return items


def _cpu_mask(items):
    bv = CPUBatchVerifier()
    for pk, m, s in items:
        bv.add(pk, m, s)
    _, mask = bv.verify()
    return mask


_seq = [0]


def _faulty(plan=None, **sup_kwargs):
    """A fresh FaultyBackend registration + supervisor over it (unique
    backend name per call — the registry is process-global)."""
    _seq[0] += 1
    name = f"test-faulty-{_seq[0]}"
    plan = install(name=name, inner="cpu",
                   plan=plan if plan is not None else FaultPlan(seed=_seq[0]))
    sup_kwargs.setdefault("dispatch_timeout_ms", 2000)
    sup_kwargs.setdefault("breaker_threshold", 3)
    sup_kwargs.setdefault("audit_pct", 0)
    sup_kwargs.setdefault("probe_base_ms", 10)
    sup_kwargs.setdefault("probe_max_ms", 80)
    sup = BackendSupervisor(spec=BackendSpec(name), **sup_kwargs)
    return plan, sup


class TestBreakerStateMachine:
    def test_healthy_path_verdicts_and_state(self):
        plan, sup = _faulty()
        items = _make_items(8, poison_at=3)
        assert sup.verify_items(items) == _cpu_mask(items)
        assert sup.state() == HEALTHY
        # mixed verdicts cost one extra device pass: the triage re-check
        # that convicts the poisoned lane (tests/test_adaptive_dispatch.py)
        assert sup.metrics.device_dispatches.value() == 2
        assert sup.metrics.triage_runs.value() == 1
        sup.stop()

    def test_failures_walk_healthy_degraded_broken(self):
        plan, sup = _faulty(breaker_threshold=3)
        items = _make_items(4)
        plan.exception_rate = 1.0
        assert sup.verify_items(items) == _cpu_mask(items)
        assert sup.state() == DEGRADED
        assert sup.verify_items(items) == _cpu_mask(items)
        assert sup.state() == DEGRADED
        assert sup.verify_items(items) == _cpu_mask(items)  # 3rd → trip
        assert sup.state() == BROKEN
        assert sup.metrics.trips.with_labels(cause="failures").value() == 1
        assert sup.metrics.failures.value() == 3
        sup.stop()

    def test_success_recovers_degraded_to_healthy(self):
        plan, sup = _faulty(breaker_threshold=3)
        items = _make_items(4)
        plan.exception_rate = 1.0
        sup.verify_items(items)
        assert sup.state() == DEGRADED
        plan.clear()
        assert sup.verify_items(items) == _cpu_mask(items)
        assert sup.state() == HEALTHY
        sup.stop()

    def test_broken_routes_to_cpu_without_touching_backend(self):
        plan, sup = _faulty(breaker_threshold=1)
        items = _make_items(4, poison_at=1)
        plan.exception_rate = 1.0
        sup.verify_items(items)
        assert sup.state() == BROKEN
        before = plan.dispatches
        for _ in range(3):
            assert sup.verify_items(items) == _cpu_mask(items)
        # the breaker short-circuits: no new backend dispatches (the
        # lazy async probe may fire, so allow at most probe traffic)
        assert sup.metrics.cpu_routed.value() == 3
        assert plan.dispatches - before <= 3  # probes only, not traffic
        sup.stop()

    def test_success_does_not_close_open_breaker(self):
        # only a canary probe may close BROKEN — a lucky dispatch must not
        plan, sup = _faulty(breaker_threshold=1)
        plan.exception_rate = 1.0
        sup.verify_items(_make_items(2))
        assert sup.state() == BROKEN
        plan.clear()
        sup._note_success(sup._domains[0])
        assert sup.state() == BROKEN
        sup.stop()

    def test_probe_readmits_after_recovery(self):
        plan, sup = _faulty(breaker_threshold=1)
        plan.exception_rate = 1.0
        sup.verify_items(_make_items(2))
        assert sup.state() == BROKEN
        plan.clear()
        assert sup.probe_now() is True
        assert sup.state() == HEALTHY
        assert sup.metrics.probes.with_labels(outcome="ok").value() == 1
        # traffic flows back to the device
        before = plan.dispatches
        items = _make_items(4)
        assert sup.verify_items(items) == _cpu_mask(items)
        assert plan.dispatches == before + 1
        sup.stop()

    def test_failed_probe_doubles_backoff_capped(self):
        plan, sup = _faulty(breaker_threshold=1, probe_base_ms=10,
                            probe_max_ms=40)
        plan.die_after = 0
        sup.verify_items(_make_items(2))
        assert sup.state() == BROKEN
        assert sup._backoff_s == pytest.approx(0.010)
        assert sup.probe_now() is False
        assert sup._backoff_s == pytest.approx(0.020)
        assert sup.probe_now() is False
        assert sup._backoff_s == pytest.approx(0.040)
        assert sup.probe_now() is False
        assert sup._backoff_s == pytest.approx(0.040)  # capped
        assert sup.metrics.probes.with_labels(outcome="fail").value() == 3
        sup.stop()

    def test_empty_and_cpu_spec_bypass_supervision(self):
        sup = BackendSupervisor(spec=BackendSpec("cpu"))
        assert sup.verify_items([]) == []
        items = _make_items(3, poison_at=0)
        assert sup.verify_items(items) == _cpu_mask(items)
        assert sup.metrics.device_dispatches.value() == 0
        sup.stop()


class TestWatchdog:
    def test_hang_is_abandoned_and_breaks_circuit(self):
        plan, sup = _faulty(dispatch_timeout_ms=200, breaker_threshold=3)
        plan.hang_rate = 1.0
        plan.hang_s = 30.0
        items = _make_items(4, poison_at=2)
        t0 = time.perf_counter()
        mask = sup.verify_items(items)
        dt = time.perf_counter() - t0
        assert mask == _cpu_mask(items)  # CPU re-verify, exact verdicts
        assert dt < 5.0, f"watchdog did not bound the hang ({dt:.1f}s)"
        # ANY watchdog trip opens the breaker immediately
        assert sup.state() == BROKEN
        assert sup.metrics.watchdog_kills.value() == 1
        assert sup.metrics.trips.with_labels(cause="watchdog").value() == 1
        sup.stop()

    def test_zombie_thread_exits_via_cancel_event(self):
        plan, sup = _faulty(dispatch_timeout_ms=200)
        plan.hang_rate = 1.0
        plan.hang_s = 30.0
        sup.verify_items(_make_items(2))
        # the abandoned thread wakes on the cancel event, NOT after 30 s
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            zombies = [
                t for t in threading.enumerate()
                if t.name == "supervised-dispatch" and t.is_alive()
            ]
            if not zombies:
                break
            time.sleep(0.02)
        assert not zombies, "abandoned dispatch thread still alive"
        sup.stop()

    def test_watchdog_timeout_type(self):
        plan, sup = _faulty(dispatch_timeout_ms=100)
        plan.hang_rate = 1.0
        plan.hang_s = 30.0
        with pytest.raises(WatchdogTimeout):
            sup._device_verify(sup._domains[0], _make_items(2))
        sup.stop()


class TestCorruptionAudit:
    def test_sync_audit_catches_corruption_before_release(self):
        plan, sup = _faulty(audit_pct=100, audit_sync=True)
        items = _make_items(6, poison_at=4)
        plan.corrupt_rate = 1.0
        # the device verdict is flipped; triage overturns the false
        # convictions (one mismatch), then the sync audit catches the
        # false accept on the poisoned lane BEFORE release (a second
        # mismatch) and the ground truth wins
        assert sup.verify_items(items) == _cpu_mask(items)
        assert sup.state() == BROKEN
        assert sup.metrics.audit_mismatches.value() == 2
        assert sup.metrics.trips.with_labels(cause="audit").value() == 1
        sup.stop()

    def test_async_audit_breaks_circuit_in_background(self):
        plan, sup = _faulty(audit_pct=100, audit_sync=False)
        # all signatures bad: corruption flips the mask to all-True, an
        # all-ok verdict that triage never re-checks (triage only chases
        # claimed-BAD lanes) — the classic silent false accept
        items = [(pk, m, b"\x00" * 64) for pk, m, _ in _make_items(6)]
        plan.corrupt_rate = 1.0
        mask = sup.verify_items(items)
        # background mode: the corrupted verdict escapes THIS batch...
        assert mask == [True] * 6
        # ...but the audit catches it and breaks the circuit shortly
        deadline = time.monotonic() + 10.0
        while sup.state() != BROKEN and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sup.state() == BROKEN
        assert sup.metrics.audit_mismatches.value() == 1
        sup.stop()

    def test_clean_batches_audit_without_tripping(self):
        plan, sup = _faulty(audit_pct=100, audit_sync=True)
        items = _make_items(5, poison_at=1)
        for _ in range(3):
            assert sup.verify_items(items) == _cpu_mask(items)
        assert sup.state() == HEALTHY
        assert sup.metrics.audits.value() == 3
        assert sup.metrics.audit_mismatches.value() == 0
        sup.stop()

    def test_audit_pct_zero_never_audits(self):
        plan, sup = _faulty(audit_pct=0)
        sup.verify_items(_make_items(4))
        assert sup.metrics.audits.value() == 0
        sup.stop()


class TestVerdictParityAllModes:
    @pytest.mark.parametrize("mode", [
        "exceptions", "dead", "corruption_sync", "jitter", "hang",
    ])
    def test_mode_never_releases_wrong_verdict(self, mode):
        kwargs = {}
        plan = FaultPlan(seed=hash(mode) & 0xFFFF)
        if mode == "exceptions":
            plan.exception_rate = 0.6
        elif mode == "dead":
            plan.die_after = 2
        elif mode == "corruption_sync":
            plan.corrupt_rate = 0.5
            kwargs = {"audit_pct": 100, "audit_sync": True}
        elif mode == "jitter":
            plan.jitter_ms = 3.0
        elif mode == "hang":
            plan.hang_rate = 0.4
            plan.hang_s = 30.0
            kwargs = {"dispatch_timeout_ms": 150}
        _, sup = _faulty(plan=plan, **kwargs)
        for i in range(6):
            items = _make_items(8, tag=bytes([i]),
                                poison_at=i % 8 if i % 2 else None)
            assert sup.verify_items(items) == _cpu_mask(items), mode
        sup.stop()


class TestFaultyBackendUnit:
    def test_exception_drops_items_like_a_real_death(self):
        plan = FaultPlan(exception_rate=1.0)
        fb = FaultyBackend(plan, CPUBatchVerifier())
        for pk, m, s in _make_items(3):
            fb.add(pk, m, s)
        assert fb.count() == 3
        with pytest.raises(FaultInjected):
            fb.verify()
        assert fb.count() == 0  # batch dropped, like a dead backend

    def test_corruption_flips_every_verdict(self):
        plan = FaultPlan(corrupt_rate=1.0)
        fb = FaultyBackend(plan, CPUBatchVerifier())
        items = _make_items(4, poison_at=2)
        for pk, m, s in items:
            fb.add(pk, m, s)
        _, mask = fb.verify()
        assert mask == [not b for b in _cpu_mask(items)]

    def test_die_after_counts_dispatches(self):
        plan = FaultPlan(die_after=2)
        name = "test-dieafter"
        cryptobatch.register_backend(
            name, lambda: FaultyBackend(plan, CPUBatchVerifier())
        )
        items = _make_items(2)
        for _ in range(2):  # dispatches 1..2 fine
            bv = new_batch_verifier(name)
            for pk, m, s in items:
                bv.add(pk, m, s)
            ok, _ = bv.verify()
            assert ok
        bv = new_batch_verifier(name)  # dispatch 3 → dead
        for pk, m, s in items:
            bv.add(pk, m, s)
        with pytest.raises(FaultInjected):
            bv.verify()

    def test_plan_from_env(self, monkeypatch):
        monkeypatch.setenv("CBFT_FAULT_EXC_RATE", "0.5")
        monkeypatch.setenv("CBFT_FAULT_DIE_AFTER", "7")
        monkeypatch.setenv("CBFT_FAULT_JITTER_MS", "2.5")
        plan = FaultPlan.from_env()
        assert plan.exception_rate == 0.5
        assert plan.die_after == 7
        assert plan.jitter_ms == 2.5
        plan.clear()
        assert plan.exception_rate == 0.0 and plan.die_after is None


class TestSchedulerIntegration:
    def test_supervised_scheduler_routes_and_flushes_broken(self):
        plan, sup = _faulty(breaker_threshold=1)
        plan.exception_rate = 1.0
        sup.verify_items(_make_items(2))  # trip it
        assert sup.state() == BROKEN
        # flush deadline 10 s out: only the broken short-circuit can
        # release this quickly
        s = VerifyScheduler(spec=sup.spec, flush_us=10_000_000,
                            supervisor=sup)
        s.start()
        try:
            items = _make_items(6, poison_at=2)
            t0 = time.perf_counter()
            ok, mask = s.submit(items).result(timeout=30)
            dt = time.perf_counter() - t0
            assert mask == _cpu_mask(items) and not ok
            assert dt < 5.0, f"broken breaker did not short-circuit ({dt:.1f}s)"
            assert s.metrics.flushes.with_labels(reason="broken").value() >= 1
        finally:
            s.stop()
            sup.stop()

    def test_supervised_scheduler_verdicts_under_faults(self):
        plan, sup = _faulty(breaker_threshold=2, audit_pct=100,
                            audit_sync=True)
        plan.exception_rate = 0.5
        plan.corrupt_rate = 0.3
        s = VerifyScheduler(spec=sup.spec, flush_us=1000, supervisor=sup)
        s.start()
        try:
            for i in range(5):
                items = _make_items(8, tag=bytes([i]),
                                    poison_at=3 if i % 2 else None)
                ok, mask = s.submit(items).result(timeout=30)
                assert mask == _cpu_mask(items)
        finally:
            s.stop()
            sup.stop()

    def test_supervisor_duck_typing(self):
        plan, sup = _faulty()
        assert unwrap_backend(sup) is sup.spec
        assert cryptobatch.backend_name(sup) == sup.spec.name
        bv = new_batch_verifier(sup)
        assert isinstance(bv, SupervisedBatchVerifier)
        items = _make_items(5, poison_at=4)
        for pk, m, s in items:
            bv.add(pk, m, s)
        assert bv.count() == 5
        ok, mask = bv.verify()
        assert not ok and mask == _cpu_mask(items)
        assert bv.verify() == (False, [])
        sup.stop()


class _GatedVerifier(CPUBatchVerifier):
    """verify() blocks until the class gate opens — a controllable
    wedged device plane for backpressure/stop tests."""

    gate = threading.Event()
    entered = threading.Event()

    def verify(self):
        _GatedVerifier.entered.set()
        _GatedVerifier.gate.wait()
        return super().verify()


@pytest.fixture()
def gated_backend():
    _GatedVerifier.gate = threading.Event()
    _GatedVerifier.entered = threading.Event()
    cryptobatch.register_backend("gated", _GatedVerifier)
    yield BackendSpec("gated")
    _GatedVerifier.gate.set()  # release any stragglers


class TestBoundedSubmit:
    def test_backpressure_blocks_then_admits(self, gated_backend):
        s = VerifyScheduler(spec=gated_backend, flush_us=500, max_queue=8)
        assert s.max_queue == 8
        s.start()
        try:
            fut_a = s.submit(_make_items(8, tag=b"a"))  # worker grabs it
            assert _GatedVerifier.entered.wait(5)
            fut_b = s.submit(_make_items(8, tag=b"b"))  # fills the queue
            done = threading.Event()
            box = {}

            def blocked_submit():
                box["fut"] = s.submit(_make_items(4, tag=b"c"))
                done.set()

            t = threading.Thread(target=blocked_submit)
            t.start()
            time.sleep(0.1)
            assert not done.is_set(), "submit should block on a full queue"
            assert s.metrics.backpressure_waits.value() == 1
            _GatedVerifier.gate.set()  # drain the plane
            assert done.wait(10), "submit never unblocked"
            for fut, n in ((fut_a, 8), (fut_b, 8), (box["fut"], 4)):
                ok, mask = fut.result(timeout=10)
                assert ok and len(mask) == n
            assert s.metrics.backpressure_timeouts.value() == 0
        finally:
            _GatedVerifier.gate.set()
            s.stop()

    def test_backpressure_timeout_verifies_inline_on_cpu(
        self, gated_backend, monkeypatch
    ):
        monkeypatch.setenv("CBFT_SUBMIT_TIMEOUT_MS", "200")
        s = VerifyScheduler(spec=gated_backend, flush_us=500, max_queue=8)
        s.start()
        try:
            s.submit(_make_items(8, tag=b"a"))
            assert _GatedVerifier.entered.wait(5)
            s.submit(_make_items(8, tag=b"b"))  # queue now full
            items = _make_items(4, tag=b"c", poison_at=1)
            t0 = time.perf_counter()
            fut = s.submit(items)  # blocks 200 ms, then inline CPU
            dt = time.perf_counter() - t0
            assert fut.done()
            ok, mask = fut.result(timeout=0)
            assert mask == _cpu_mask(items) and not ok
            assert 0.15 <= dt < 5.0
            assert s.metrics.backpressure_timeouts.value() == 1
        finally:
            _GatedVerifier.gate.set()
            s.stop()

    def test_oversize_request_admitted_when_queue_empty(self, gated_backend):
        _GatedVerifier.gate.set()  # plane healthy
        s = VerifyScheduler(spec=gated_backend, flush_us=500, max_queue=4)
        s.start()
        try:
            # 16 > max_queue=4, but the queue is empty: it must pass
            ok, mask = s.submit(_make_items(16)).result(timeout=10)
            assert ok and len(mask) == 16
            assert s.metrics.backpressure_waits.value() == 0
        finally:
            s.stop()

    def test_max_queue_knob_precedence(self, monkeypatch):
        from cometbft_tpu.crypto.scheduler import (
            DEFAULT_MAX_QUEUE,
            max_queue_default,
        )

        monkeypatch.delenv("CBFT_MAX_QUEUE", raising=False)
        assert max_queue_default() == DEFAULT_MAX_QUEUE
        assert max_queue_default(123) == 123
        monkeypatch.setenv("CBFT_MAX_QUEUE", "77")
        assert max_queue_default(123) == 77


class TestStopJoinFailure:
    def test_failed_join_fails_pending_futures(self, gated_backend):
        s = VerifyScheduler(spec=gated_backend, flush_us=500,
                            join_timeout_s=0.2)
        s.start()
        fut_a = s.submit(_make_items(4, tag=b"a"))  # wedges the worker
        assert _GatedVerifier.entered.wait(5)
        fut_b = s.submit(_make_items(4, tag=b"b"))  # left queued
        s.stop()  # join times out after 0.2 s
        for fut in (fut_a, fut_b):
            assert fut.done()
            with pytest.raises(RuntimeError, match="wedged"):
                fut.result(timeout=0)
        # the zombie worker limping home must NOT overwrite the error
        # (first-wins completion)
        _GatedVerifier.gate.set()
        time.sleep(0.3)
        with pytest.raises(RuntimeError, match="wedged"):
            fut_a.result(timeout=0)

    def test_clean_join_still_drains(self, gated_backend):
        _GatedVerifier.gate.set()
        s = VerifyScheduler(spec=gated_backend, flush_us=10_000_000,
                            lane_budget=4096, join_timeout_s=5.0)
        s.start()
        fut = s.submit(_make_items(4))
        s.stop()
        ok, mask = fut.result(timeout=5)
        assert ok and len(mask) == 4


class TestStopMidProbe:
    def test_stop_joins_inflight_probe(self, gated_backend):
        # a warmup canary wedges on the device plane; stop() must join
        # the probe thread (bounded by the dispatch watchdog) instead of
        # leaving a daemon probe to touch the torn-down backend later
        sup = BackendSupervisor(
            spec=gated_backend, dispatch_timeout_ms=300,
            breaker_threshold=3, audit_pct=0,
            probe_base_ms=10, probe_max_ms=80,
        )
        sup.warmup_canary()
        assert _GatedVerifier.entered.wait(5)  # probe is on the device
        t0 = time.monotonic()
        sup.stop()
        # the probe abandons its wedged dispatch at the watchdog bound,
        # so the join is bounded too (well under timeout_s + 5)
        assert time.monotonic() - t0 < 5.0
        assert not any(
            t.name in ("supervisor-probe", "supervisor-canary")
            and t.is_alive()
            for t in threading.enumerate()
        )
        # after stop, probe_now is a no-op that never dispatches
        _GatedVerifier.entered.clear()
        assert sup.probe_now() is False
        assert not _GatedVerifier.entered.is_set()

    def test_stop_idempotent_after_probe_join(self, gated_backend):
        _GatedVerifier.gate.set()
        sup = BackendSupervisor(
            spec=gated_backend, dispatch_timeout_ms=300,
            breaker_threshold=3, audit_pct=0,
            probe_base_ms=10, probe_max_ms=80,
        )
        sup.warmup_canary()
        sup.stop()
        sup.stop()  # second stop must not raise or hang
        assert sup.probe_now() is False


class TestMeshCancellation:
    def test_cancel_scope_installs_and_restores(self):
        from cometbft_tpu.crypto.tpu import mesh

        assert mesh.current_cancel_event() is None
        ev1, ev2 = threading.Event(), threading.Event()
        with mesh.cancel_scope(ev1):
            assert mesh.current_cancel_event() is ev1
            with mesh.cancel_scope(ev2):
                assert mesh.current_cancel_event() is ev2
            assert mesh.current_cancel_event() is ev1
        assert mesh.current_cancel_event() is None

    def test_cancel_scope_is_thread_local(self):
        from cometbft_tpu.crypto.tpu import mesh

        ev = threading.Event()
        seen = {}

        def other():
            seen["ev"] = mesh.current_cancel_event()

        with mesh.cancel_scope(ev):
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert seen["ev"] is None

    def test_dispatch_batch_raises_cancelled(self):
        import numpy as np

        from cometbft_tpu.crypto.tpu import mesh

        def packed(start, end):
            return [np.ones(end - start, np.float32)]

        ev = threading.Event()
        ev.set()
        with mesh.cancel_scope(ev):
            with pytest.raises(mesh.DispatchCancelled, match="chunk 0"):
                mesh.dispatch_batch(lambda x: x > 0, packed, 16, 8, 8)

    def test_chunk_errors_carry_chunk_index(self):
        from cometbft_tpu.crypto.tpu import mesh

        def packed(start, end):
            if start >= 8:
                raise ValueError("link died")
            import numpy as np

            return [np.ones(end - start, np.float32)]

        with pytest.raises(RuntimeError, match=r"chunk 1 \(sigs \[8:16\]\)"):
            mesh.dispatch_batch(lambda x: x > 0, packed, 16, 8, 8)

    def test_hang_wakes_on_cancel(self):
        from cometbft_tpu.crypto.faults import _interruptible_hang
        from cometbft_tpu.crypto.tpu import mesh

        ev = threading.Event()
        box = {}

        def run():
            try:
                with mesh.cancel_scope(ev):
                    _interruptible_hang(30.0)
            except mesh.DispatchCancelled:
                box["cancelled"] = True

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.05)
        ev.set()
        t.join(timeout=5)
        assert not t.is_alive() and box.get("cancelled")


class TestKnobsAndConfig:
    def test_supervisor_knob_precedence(self, monkeypatch):
        for env in ("CBFT_DISPATCH_TIMEOUT_MS", "CBFT_BREAKER_THRESHOLD",
                    "CBFT_AUDIT_PCT"):
            monkeypatch.delenv(env, raising=False)
        assert dispatch_timeout_ms_default() == 60_000
        assert dispatch_timeout_ms_default(5000) == 5000
        assert breaker_threshold_default() == 3
        assert audit_pct_default() == 5
        monkeypatch.setenv("CBFT_DISPATCH_TIMEOUT_MS", "250")
        monkeypatch.setenv("CBFT_BREAKER_THRESHOLD", "9")
        monkeypatch.setenv("CBFT_AUDIT_PCT", "50")
        assert dispatch_timeout_ms_default(5000) == 250
        assert breaker_threshold_default(7) == 9
        assert audit_pct_default(1) == 50

    def test_supervisor_reads_config_values(self):
        sup = BackendSupervisor(
            spec=BackendSpec("tpu"), dispatch_timeout_ms=1234,
            breaker_threshold=5, audit_pct=42,
        )
        assert sup.dispatch_timeout_ms == 1234
        assert sup.breaker_threshold == 5
        assert sup.audit_pct == 42
        sup.stop()

    def test_config_defaults_and_validation(self):
        from cometbft_tpu.config import default_config

        cfg = default_config()
        assert cfg.crypto.dispatch_timeout_ms == 60_000
        assert cfg.crypto.breaker_threshold == 3
        assert cfg.crypto.audit_pct == 5
        assert cfg.crypto.max_queue == 65_536
        cfg.validate_basic()
        cfg.crypto.audit_pct = 0  # off is legal
        cfg.validate_basic()
        for knob, bad in (
            ("dispatch_timeout_ms", 0), ("breaker_threshold", -1),
            ("max_queue", 0), ("audit_pct", 101), ("audit_pct", -1),
        ):
            fresh = default_config()
            setattr(fresh.crypto, knob, bad)
            with pytest.raises(ValueError, match=knob):
                fresh.validate_basic()

    def test_config_toml_round_trip(self, tmp_path):
        from cometbft_tpu.config import (
            default_config,
            load_config_file,
            write_config_file,
        )

        cfg = default_config()
        cfg.crypto.dispatch_timeout_ms = 777
        cfg.crypto.breaker_threshold = 4
        cfg.crypto.audit_pct = 11
        cfg.crypto.max_queue = 2048
        path = str(tmp_path / "config.toml")
        write_config_file(path, cfg)
        loaded = load_config_file(path)
        assert loaded.crypto.dispatch_timeout_ms == 777
        assert loaded.crypto.breaker_threshold == 4
        assert loaded.crypto.audit_pct == 11
        assert loaded.crypto.max_queue == 2048


class TestChaosSoak:
    def test_mini_soak_invariants(self):
        summary = run_chaos_soak(
            n_blocks=8, batch=16, seed=42, dispatch_timeout_ms=300,
            probe_base_ms=15,
        )
        assert summary["wrong_verdicts"] == 0
        assert summary["lost_futures"] == 0
        assert summary["readmitted"] is True
        assert summary["device_resumed_after_recovery"] is True
        assert summary["final_state"] == HEALTHY

    @pytest.mark.slow
    def test_full_soak(self):
        summary = run_chaos_soak(
            n_blocks=40, batch=48, seed=1234, dispatch_timeout_ms=400,
            probe_base_ms=20,
        )
        assert summary["wrong_verdicts"] == 0
        assert summary["lost_futures"] == 0
        assert summary["readmitted"] is True
        assert summary["device_resumed_after_recovery"] is True
        # the schedule must actually have exercised faults
        assert summary["backend_dispatches"] > 0
