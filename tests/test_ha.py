"""HA verify fleet (PR 20): replicated verifyd endpoints behind
``HAVerifier`` — per-request failover on drain and kill, the all-down
local-CPU rung with exact reason attribution, breaker quarantine plus
probe re-admission, the verifyd graceful-drain timeout, and the chaos
rung as a fast tier-1 gate. Runs real scheduler+service daemons over
Unix sockets on the virtual CPU mesh (conftest.py)."""

import os
import sys
import threading
import time

import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto import ha as halib
from cometbft_tpu.crypto import service as svc
from cometbft_tpu.crypto.scheduler import VerifyScheduler

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)


def _batch(n, tag=b"ha", bad=()):
    keys = [ed.gen_priv_key_from_secret(tag + b"-%d" % i) for i in range(n)]
    items = []
    for i, k in enumerate(keys):
        msg = tag + b" msg %d" % i
        sig = k.sign(msg)
        if i in bad:
            sig = bytes(sig[:-1]) + bytes([sig[-1] ^ 0x01])
        items.append((k.pub_key(), msg, sig))
    return items


def _expected(items):
    return [
        ed.PubKeyEd25519(svc._pk_bytes(pk)).verify_signature(m, s)
        for pk, m, s in items
    ]


def _wait(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class _Node:
    """One restartable scheduler+service replica on a fixed socket."""

    def __init__(self, tag, idx, auth_key=None):
        self.tag, self.idx, self.auth_key = tag, idx, auth_key
        self.path = "/tmp/cbft-test-ha-%s-%d-%d.sock" % (
            tag, idx, os.getpid()
        )
        self.address = "unix://" + self.path
        self.running = False
        self._build()

    def _build(self):
        self.sched = VerifyScheduler(
            spec="cpu", flush_us=200, lane_budget=256,
            max_queue=256, qos="off",
        )
        self.service = svc.VerifyService(
            self.sched, self.address, auth_key=self.auth_key,
        )

    def start(self):
        self.sched.start()
        self.service.start()
        self.running = True

    def stop(self):
        if not self.running:
            return
        self.running = False
        self.service.stop()
        self.sched.stop()

    def restart(self):
        self._build()
        self.start()


@pytest.fixture
def fleet(request):
    tag = request.node.name.replace("[", "-").replace("]", "")[:32]
    nodes = [_Node(tag, i) for i in range(2)]
    for n in nodes:
        n.start()
    verifiers = []

    def make_hv(**kw):
        kw.setdefault("tenant", "committee")
        kw.setdefault("timeout_ms", 4000)
        kw.setdefault("connect_timeout_s", 0.5)
        kw.setdefault("retry_s", 0.05)
        kw.setdefault("retry_cap_s", 1.0)
        kw.setdefault("probe_base_s", 0.05)
        kw.setdefault("probe_cap_s", 0.5)
        kw.setdefault("seed", 11)
        hv = halib.HAVerifier([n.address for n in nodes], **kw)
        verifiers.append(hv)
        return hv

    yield nodes, make_hv
    for hv in verifiers:
        hv.close()
    for n in nodes:
        n.stop()
        try:
            os.unlink(n.path)
        except OSError:
            pass


class TestFailover:
    def test_silent_drain_fails_over_without_touching_cpu(self, fleet):
        nodes, make_hv = fleet
        hv = make_hv()
        items = _batch(6, tag=b"drain-fo", bad=(2,))
        want = _expected(items)
        for _ in range(6):
            ok, mask = hv.submit(
                items, subsystem="consensus"
            ).result(timeout=20)
            assert not ok and mask == want
        # silent drain: no FT_DRAINING broadcast, so the NEXT request
        # routed here eats a typed ST_DRAINING and must fail over
        nodes[0].service.drain(broadcast=False)
        saw = False
        for _ in range(40):
            fut = hv.submit(items, subsystem="consensus")
            ok, mask = fut.result(timeout=20)
            assert not ok and mask == want
            r = getattr(fut, "reason", None)
            assert r in (None, "failover"), r
            if r == "failover":
                saw = True
                break
        assert saw, hv.stats()
        s = hv.stats()
        assert s.get("failovers", 0) >= 1
        assert s.get("cpu_fallback", 0) == 0
        # exact attribution: the drained endpoint's client recorded the
        # transport reason, and "draining" is failover-eligible
        ep_stats = dict(hv.endpoints())[nodes[0].address].stats()
        assert ep_stats.get("draining", 0) >= 1
        assert "draining" in svc.FAILOVER_REASONS

    def test_hard_kill_fails_over_with_disconnect_attribution(self, fleet):
        nodes, make_hv = fleet
        hv = make_hv()
        items = _batch(4, tag=b"kill-fo")
        for _ in range(6):
            ok, mask = hv.submit(
                items, subsystem="consensus"
            ).result(timeout=20)
            assert ok and mask == [True] * 4
        nodes[1].stop()
        saw = False
        for _ in range(40):
            fut = hv.submit(items, subsystem="consensus")
            ok, mask = fut.result(timeout=20)
            assert ok and mask == [True] * 4
            if getattr(fut, "reason", None) == "failover":
                saw = True
                break
        assert saw, hv.stats()
        ep_stats = dict(hv.endpoints())[nodes[1].address].stats()
        assert ep_stats.get("disconnected", 0) >= 1

    def test_all_down_resolves_on_cpu_with_first_reason(self):
        dead = [
            "unix:///tmp/cbft-test-ha-void-%d-%d.sock" % (i, os.getpid())
            for i in range(2)
        ]
        hv = halib.HAVerifier(
            dead, tenant="lonely", timeout_ms=2000,
            connect_timeout_s=0.2, retry_s=0.05, retry_cap_s=0.5,
            probe_base_s=10.0, seed=3,
        )
        try:
            items = _batch(5, tag=b"all-down", bad=(0, 4))
            fut = hv.submit(items, subsystem="consensus")
            ok, mask = fut.result(timeout=20)
            # ground truth from the local CPU rung, reason = what took
            # the fleet out (never the generic "failover")
            assert not ok and mask == _expected(items)
            assert fut.reason == "disconnected"
            s = hv.stats()
            assert s.get("all_down", 0) >= 1
            assert s.get("cpu_fallback", 0) >= 1
            assert s.get("cpu_disconnected", 0) >= 1
            assert s.get("failovers", 0) == 0
        finally:
            hv.close()


class TestBreaker:
    def test_quarantine_blocks_picks_until_probe_readmission(self, fleet):
        nodes, make_hv = fleet
        hv = make_hv(breaker_threshold=2)
        items = _batch(3, tag=b"breaker")
        for _ in range(6):
            ok, _ = hv.submit(
                items, subsystem="consensus"
            ).result(timeout=20)
            assert ok
        nodes[0].stop()
        # traffic strikes knock the dead endpoint out of HEALTHY, then
        # its own failed probes escalate it to BROKEN even while the
        # healthy peer absorbs every live pick
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            ok, _ = hv.submit(
                items, subsystem="consensus"
            ).result(timeout=20)
            assert ok
            if hv.endpoint_state(nodes[0].address) == halib.BROKEN:
                break
            time.sleep(0.02)
        assert hv.endpoint_state(nodes[0].address) == halib.BROKEN, \
            hv.snapshot()
        assert hv.stats().get("breaker_opens", 0) >= 1
        picks_before = [
            e for e in hv.snapshot()["endpoints"]
            if e["address"] == nodes[0].address
        ][0]["picks"]
        for _ in range(12):
            fut = hv.submit(items, subsystem="consensus")
            ok, _ = fut.result(timeout=20)
            assert ok and getattr(fut, "reason", None) is None
        picks_after = [
            e for e in hv.snapshot()["endpoints"]
            if e["address"] == nodes[0].address
        ][0]["picks"]
        assert picks_after == picks_before
        # the breaker re-opens ONLY via the health probe
        nodes[0].restart()
        assert _wait(
            lambda: hv.endpoint_state(nodes[0].address) == halib.HEALTHY
        ), hv.snapshot()
        assert hv.stats().get("probe_readmissions", 0) >= 1


class TestChaosHaRung:
    def test_chaos_ha_fast(self):
        """The chaos rung itself as a tier-1 gate: two replicas through
        rolling drain-restart, hard kill, blackhole, and a wrong-key
        client — zero wrong verdicts, zero rolling CPU fallbacks, exact
        attribution, quarantine + re-admission."""
        from cometbft_tpu.crypto.faults import run_chaos_ha

        s = run_chaos_ha(seed=5, replicas=2, load_threads=2)
        assert s["wrong_verdicts"] == 0
        assert s["rolling_failovers"] >= 2
        assert s["rolling_cpu_fallbacks"] == 0
        assert s["rolling_readmits"] == 2
        assert s["kill_failovers"] >= 1
        assert s["failover_gap_p99_ms"] <= s["failover_gap_bound_ms"]
        assert s["blackhole_quarantined"] is True
        assert s["quarantine_picks_leaked"] == 0
        assert s["probe_readmitted"] is True
        assert s["failover_reasons"].get("draining", 0) >= 2
        assert s["failover_reasons"].get("disconnected", 0) >= 1
        assert s["evil_unauthorized"] >= 1
        assert s["server_auth_rejects"] >= 1
        assert s["evil_requests_served"] == 0


class TestVerifydDrainTimeout:
    def test_drain_timeout_abandons_and_counts(self, tmp_path):
        import verifyd

        gate = threading.Event()
        inner = svc.host_row_verifier()

        def gated(rows):
            gate.wait(20)
            return inner(rows)

        path = "/tmp/cbft-test-ha-vd-%d.sock" % os.getpid()
        d = verifyd.Daemon(
            "unix://" + path, backend="cpu", flush_us=200,
            metrics_addr="127.0.0.1:0", dump_dir=str(tmp_path),
            row_verifier=gated, drain_timeout_ms=300,
        )
        d.start()
        c = svc.RemoteVerifier(
            d.service.address(), tenant="stuck", timeout_ms=15_000,
            retry_s=0.05,
        )
        try:
            items = _batch(4, tag=b"vd-drain")
            fut = c.submit(items, subsystem="consensus")
            assert _wait(lambda: d.service.pending_requests() >= 1)
            # the pool never thaws: the bounded drain must give up and
            # report exactly how many frames it abandoned
            t0 = time.monotonic()
            abandoned = d.drain()
            assert abandoned >= 1
            assert time.monotonic() - t0 < 5.0
            assert d.service.draining
        finally:
            gate.set()
            fut.result(timeout=20)
            c.close()
            d.stop()
            try:
                os.unlink(path)
            except OSError:
                pass

    def test_drain_waits_out_inflight_when_it_completes(self, tmp_path):
        import verifyd

        gate = threading.Event()
        inner = svc.host_row_verifier()

        def gated(rows):
            gate.wait(20)
            return inner(rows)

        path = "/tmp/cbft-test-ha-vd2-%d.sock" % os.getpid()
        d = verifyd.Daemon(
            "unix://" + path, backend="cpu", flush_us=200,
            metrics_addr="127.0.0.1:0", dump_dir=str(tmp_path),
            row_verifier=gated, drain_timeout_ms=10_000,
        )
        d.start()
        c = svc.RemoteVerifier(
            d.service.address(), tenant="patient", timeout_ms=15_000,
            retry_s=0.05,
        )
        try:
            items = _batch(3, tag=b"vd-wait", bad=(1,))
            fut = c.submit(items, subsystem="consensus")
            assert _wait(lambda: d.service.pending_requests() >= 1)
            done = []
            t = threading.Thread(
                target=lambda: done.append(d.drain()), daemon=True
            )
            t.start()
            time.sleep(0.1)
            gate.set()
            t.join(timeout=10)
            assert done == [0]
            ok, mask = fut.result(timeout=20)
            assert not ok and mask == _expected(items)
            assert getattr(fut, "reason", None) is None
        finally:
            gate.set()
            c.close()
            d.stop()
            try:
                os.unlink(path)
            except OSError:
                pass


class TestHaBenchDirections:
    def test_sentinel_directions_for_the_ha_stage(self):
        import importlib.util

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_history_ha_test",
            os.path.join(repo, "tools", "bench_history.py"),
        )
        bh = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bh)
        for leaf in ("ha_failover_gap_ms",
                     "stages.ha.ha_failover_gap_ms"):
            assert bh.direction(leaf) == bh.LOWER_IS_BETTER, leaf
        for leaf in ("ha_rolling_cpu_fallbacks", "ha_wrong_verdicts",
                     "stages.ha.ha_rolling_cpu_fallbacks"):
            assert bh.direction(leaf) == bh.LOWER_IS_BETTER, leaf
        assert (bh.direction("stages.ha.ha_fleet_sigs_per_sec")
                == bh.HIGHER_IS_BETTER)
        # ratios and booleans stay directionless
        assert bh.direction("ha_fleet_gain") is None
        assert bh.direction("ha_probe_readmitted") is None
