"""Mempool: CListMempool unit tests + reactor gossip over real TCP.

Model: reference mempool/v0/clist_mempool_test.go (CheckTx/Reap/Update/
recheck/cache) and mempool/v0/reactor_test.go (txs broadcast between
switches, no re-send to the origin peer).
"""

import threading
import time

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import LocalClient
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import test_config as make_test_config
from cometbft_tpu.mempool import (
    ErrMempoolIsFull,
    ErrTxInCache,
    ErrTxTooLarge,
)
from cometbft_tpu.mempool.clist_mempool import CListMempool, TxInfo
from cometbft_tpu.mempool.reactor import (
    MEMPOOL_CHANNEL,
    MempoolIDs,
    MempoolReactor,
    decode_txs_message,
    encode_txs_message,
)
from cometbft_tpu.proxy import AppConnMempool


class CounterApp(KVStoreApplication):
    """App that rejects txs below a height-scoped threshold so recheck can
    invalidate previously-valid txs (model: abci counter app)."""

    def __init__(self):
        super().__init__()
        self.reject_below = 0

    def check_tx(self, req):
        try:
            v = int(req.tx.decode())
        except ValueError:
            return abci.ResponseCheckTx(code=1, log="not a number")
        if v < self.reject_below:
            return abci.ResponseCheckTx(code=2, log="below threshold")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)


def _mk_mempool(app=None, **cfg_over):
    cfg = make_test_config().mempool
    for k, v in cfg_over.items():
        setattr(cfg, k, v)
    app = app or CounterApp()
    client = LocalClient(app)
    client.start()
    mp = CListMempool(cfg, AppConnMempool(client), height=0)
    return mp, app, client


def _check(mp, tx: bytes, sender="") -> None:
    mp.check_tx(tx, None, TxInfo(sender_id=sender))


class TestCListMempool:
    def test_check_tx_appends_and_reaps_fifo(self):
        mp, _, _ = _mk_mempool()
        for i in range(10):
            _check(mp, str(i).encode())
        assert mp.size() == 10
        assert mp.size_bytes() == sum(len(str(i)) for i in range(10))
        # FIFO order
        assert mp.reap_max_txs(-1) == [str(i).encode() for i in range(10)]

    def test_cache_rejects_duplicates(self):
        mp, _, _ = _mk_mempool()
        _check(mp, b"1")
        with pytest.raises(ErrTxInCache):
            _check(mp, b"1")
        assert mp.size() == 1

    def test_duplicate_from_peer_records_sender(self):
        mp, _, _ = _mk_mempool()
        _check(mp, b"1", sender="peerA")
        with pytest.raises(ErrTxInCache):
            _check(mp, b"1", sender="peerB")
        elem = mp.txs_front()
        assert elem.value.senders == {"peerA", "peerB"}

    def test_tx_too_large(self):
        mp, _, _ = _mk_mempool(max_tx_bytes=10)
        with pytest.raises(ErrTxTooLarge):
            _check(mp, b"x" * 11)

    def test_mempool_full(self):
        mp, _, _ = _mk_mempool(size=2)
        _check(mp, b"1")
        _check(mp, b"2")
        with pytest.raises(ErrMempoolIsFull):
            _check(mp, b"3")

    def test_invalid_tx_not_added_and_cache_evicted(self):
        mp, app, _ = _mk_mempool()
        _check(mp, b"notanumber")
        assert mp.size() == 0
        # not kept in cache (keep_invalid_txs_in_cache=False default):
        # a later resubmission reaches the app again
        app.reject_below = 0
        _check(mp, b"notanumber")  # no ErrTxInCache raised
        assert mp.size() == 0

    def test_reap_max_bytes_max_gas(self):
        mp, _, _ = _mk_mempool()
        for i in range(10, 20):  # 2-byte txs
            _check(mp, str(i).encode())
        assert len(mp.reap_max_bytes_max_gas(-1, -1)) == 10
        # byte budget counts proto framing (1 tag + 1 len + 2 payload = 4
        # per tx, as ComputeProtoSizeForTxs does): 12 bytes → 3 txs
        assert len(mp.reap_max_bytes_max_gas(12, -1)) == 3
        assert len(mp.reap_max_bytes_max_gas(11, -1)) == 2
        # gas budget: each tx wants 1 gas
        assert len(mp.reap_max_bytes_max_gas(-1, 4)) == 4
        # zero budget
        assert mp.reap_max_bytes_max_gas(0, -1) == []

    def test_update_removes_committed_and_caches_them(self):
        mp, _, _ = _mk_mempool()
        for i in range(5):
            _check(mp, str(i).encode())
        ok = abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)
        mp.lock()
        try:
            mp.update(1, [b"0", b"1"], [ok, ok])
        finally:
            mp.unlock()
        assert mp.reap_max_txs(-1) == [b"2", b"3", b"4"]
        # committed txs stay cached: re-broadcast is dropped
        with pytest.raises(ErrTxInCache):
            _check(mp, b"0")

    def test_recheck_drops_now_invalid_txs(self):
        mp, app, _ = _mk_mempool()
        for i in range(6):
            _check(mp, str(i).encode())
        # commit "0"; app now rejects everything below 4
        app.reject_below = 4
        ok = abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)
        mp.lock()
        try:
            mp.update(1, [b"0"], [ok])
        finally:
            mp.unlock()
        # recheck ran synchronously through LocalClient: 1,2,3 dropped
        assert mp.reap_max_txs(-1) == [b"4", b"5"]

    def test_txs_available_notification(self):
        mp, _, _ = _mk_mempool()
        mp.enable_txs_available()
        fired = []
        mp.on_txs_available = lambda: fired.append(1)
        assert not mp.txs_available()
        _check(mp, b"7")
        assert mp.txs_available()
        assert fired == [1]
        # only notified once per height
        _check(mp, b"8")
        assert fired == [1]
        ok = abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)
        mp.lock()
        try:
            mp.update(1, [b"7"], [ok])
        finally:
            mp.unlock()
        # survivors present → re-notified for next height
        assert fired == [1, 1]

    def test_flush(self):
        mp, _, _ = _mk_mempool()
        for i in range(3):
            _check(mp, str(i).encode())
        mp.flush()
        assert mp.size() == 0 and mp.size_bytes() == 0
        # cache reset too: same tx is accepted again
        _check(mp, b"0")
        assert mp.size() == 1


class TestTxsMessageCodec:
    def test_roundtrip(self):
        txs = [b"a", b"bb", b"\x00" * 100]
        assert decode_txs_message(encode_txs_message(txs)) == txs

    def test_empty(self):
        assert decode_txs_message(encode_txs_message([])) == []


class TestMempoolIDs:
    def test_reserve_reclaim(self):
        class P:
            def __init__(self, i):
                self._i = f"peer{i}"

            def id(self):
                return self._i

        ids = MempoolIDs()
        p1, p2 = P(1), P(2)
        assert ids.reserve_for_peer(p1) == 1
        assert ids.reserve_for_peer(p2) == 2
        assert ids.get_for_peer(p1) == 1
        ids.reclaim(p1)
        assert ids.get_for_peer(p1) == 0  # unknown
        p3 = P(3)
        assert ids.reserve_for_peer(p3) == 1  # reuses freed slot
