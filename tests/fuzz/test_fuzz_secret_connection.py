"""Adversarial SecretConnection handshakes + frame fuzzing.

Model: reference test/fuzz (go-fuzz SecretConnection Read/Write targets)
and p2p/conn/evil_secret_connection_test.go — an evil peer that departs
from the STS protocol at every step: withheld or malformed ephemeral
keys, low-order X25519 points, withheld or forged auth signatures, and
garbage ciphertext frames. The honest side must either complete with the
right peer identity or fail with a CLEAN error (HandshakeError /
ConnectionError / ValueError) — never hang, never die on an unexpected
exception class. A from-scratch STROBE/merlin + hand-rolled framing is
exactly the code that needs this.
"""

from __future__ import annotations

import os
import socket
import struct
import threading

import pytest

try:
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.hashes import SHA256
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
except ImportError:  # slim image: the same fallback the module under test uses
    from cometbft_tpu.crypto.purepy import (
        ChaCha20Poly1305,
        HKDF,
        SHA256,
        X25519PrivateKey,
        X25519PublicKey,
    )

from cometbft_tpu.crypto import ed25519
from cometbft_tpu.crypto.merlin import Transcript
from cometbft_tpu.libs import protoio
from cometbft_tpu.p2p.conn import secret_connection as sconn
from cometbft_tpu.p2p.conn.secret_connection import (
    HandshakeError,
    SecretConnection,
)
from cometbft_tpu.proto.keys import pub_key_to_proto

CLEAN = (HandshakeError, ConnectionError, ValueError, OSError)

# canonical small-order curve25519 points (the reference's blacklist,
# secret_connection.go:44)
LOW_ORDER_POINTS = [
    bytes(32),
    (1).to_bytes(32, "little"),
    bytes.fromhex(
        "e0eb7a7c3b41b8ae1656e3faf19fc46ada098deb9c32b1fd866205165f49b800"
    ),
    bytes.fromhex(
        "5f9c95bca3508c24b1d0b1559c83ef5b04445cc4581c8e86d8224eddd09f1157"
    ),
    bytes.fromhex(
        "ecffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f"
    ),
    bytes.fromhex(
        "edffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f"
    ),
]


def _pair():
    a, b = socket.socketpair()
    a.settimeout(10)
    b.settimeout(10)
    return a, b


def _handshake_result(sock, key):
    """Run the honest handshake in a thread; → ('ok', conn) | ('err', exc)."""
    box = {}

    def run():
        try:
            box["conn"] = SecretConnection.make(sock, key)
        except Exception as exc:  # noqa: BLE001 — classified by the caller
            box["exc"] = exc

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=15)
    assert not t.is_alive(), "handshake hung against adversarial peer"
    if "conn" in box:
        return "ok", box["conn"]
    return "err", box["exc"]


def _evil_peer(
    sock,
    share_eph=True,
    eph_payload: bytes | None = None,
    share_sig=True,
    bad_sig=False,
):
    """The evil half: follows the protocol only as far as configured."""
    try:
        if not share_eph:
            sock.close()
            return
        eph_priv = X25519PrivateKey.generate()
        pub = (
            eph_payload
            if eph_payload is not None
            else eph_priv.public_key().public_bytes_raw()
        )
        sock.sendall(protoio.marshal_delimited(protoio.field_bytes(1, pub)))
        msg = sconn._read_delimited_from_sock(sock, 1 << 20)
        r = protoio.WireReader(msg)
        rem_eph = b""
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                rem_eph = r.read_bytes()
            else:
                r.skip(wt)
        if not share_sig:
            sock.close()
            return
        if eph_payload is not None:
            sock.close()  # can't derive keys from a lie; bail
            return
        # derive the same keys the honest side will
        loc_pub = eph_priv.public_key().public_bytes_raw()
        lo, hi = sorted([loc_pub, rem_eph])
        transcript = Transcript(sconn._TRANSCRIPT_LABEL)
        transcript.append_message(sconn._LABEL_EPH_LO, lo)
        transcript.append_message(sconn._LABEL_EPH_HI, hi)
        dh = eph_priv.exchange(X25519PublicKey.from_public_bytes(rem_eph))
        transcript.append_message(sconn._LABEL_DH_SECRET, dh)
        okm = HKDF(
            algorithm=SHA256(), length=96, salt=None, info=sconn._HKDF_INFO
        ).derive(dh)
        if loc_pub == lo:
            recv_key, send_key = okm[0:32], okm[32:64]
        else:
            send_key, recv_key = okm[0:32], okm[32:64]
        challenge = transcript.extract_bytes(sconn._LABEL_MAC, 32)
        conn = SecretConnection(sock, send_key, recv_key, rem_pub_key=None)
        key = ed25519.gen_priv_key()
        sig = os.urandom(64) if bad_sig else key.sign(challenge)
        auth = protoio.field_message(
            1, pub_key_to_proto(key.pub_key()).encode()
        ) + protoio.field_bytes(2, sig)
        conn.write(protoio.marshal_delimited(auth))
        try:
            conn._read_delimited(1 << 20)
        except Exception:
            pass
    except Exception:
        pass
    finally:
        try:
            sock.close()
        except OSError:
            pass


class TestEvilHandshakes:
    """The evil_secret_connection_test.go matrix."""

    @pytest.mark.parametrize(
        "share_eph,eph_payload,share_sig,bad_sig,expect_ok",
        [
            (False, None, False, False, False),  # shares nothing
            (True, b"drop users;", False, False, False),  # garbage eph key
            (True, None, False, False, False),  # withholds auth sig
            (True, None, True, True, False),  # forged auth sig
            (True, None, True, False, True),  # fully honest peer
        ],
    )
    def test_matrix(self, share_eph, eph_payload, share_sig, bad_sig, expect_ok):
        a, b = _pair()
        t = threading.Thread(
            target=_evil_peer,
            args=(b,),
            kwargs=dict(
                share_eph=share_eph,
                eph_payload=eph_payload,
                share_sig=share_sig,
                bad_sig=bad_sig,
            ),
            daemon=True,
        )
        t.start()
        status, result = _handshake_result(a, ed25519.gen_priv_key())
        if expect_ok:
            assert status == "ok", f"honest peer rejected: {result}"
        else:
            assert status == "err"
            assert isinstance(result, CLEAN), (
                f"dirty failure {type(result).__name__}: {result}"
            )
        a.close()

    @pytest.mark.parametrize("point", LOW_ORDER_POINTS)
    def test_low_order_points_rejected(self, point):
        a, b = _pair()
        t = threading.Thread(
            target=_evil_peer, args=(b,), kwargs=dict(eph_payload=point),
            daemon=True,
        )
        t.start()
        status, result = _handshake_result(a, ed25519.gen_priv_key())
        assert status == "err"
        assert isinstance(result, CLEAN), (
            f"low-order point produced {type(result).__name__}: {result}"
        )
        a.close()

    def test_oversized_eph_key_rejected(self):
        a, b = _pair()

        def peer():
            try:
                b.sendall(
                    protoio.marshal_delimited(
                        protoio.field_bytes(1, os.urandom(33))
                    )
                )
                sconn._read_delimited_from_sock(b, 1 << 20)
            except Exception:
                pass

        threading.Thread(target=peer, daemon=True).start()
        status, result = _handshake_result(a, ed25519.gen_priv_key())
        assert status == "err" and isinstance(result, CLEAN)
        a.close()


class _TapSock:
    """Socket wrapper (sockets have read-only attrs): lets tests capture
    or inject raw bytes under an established SecretConnection."""

    def __init__(self, sock):
        self.sock = sock
        self.on_send = None

    def sendall(self, data):
        if self.on_send is not None:
            self.on_send(bytes(data))
        self.sock.sendall(data)

    def recv(self, n):
        return self.sock.recv(n)

    def close(self):
        self.sock.close()


def _good_pair():
    """Two honest sides of a completed handshake (A's socket tapped)."""
    a, b = _pair()
    tap = _TapSock(a)
    ka, kb = ed25519.gen_priv_key(), ed25519.gen_priv_key()
    box = {}

    def run():
        box["b"] = SecretConnection.make(b, kb)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    conn_a = SecretConnection.make(tap, ka)
    t.join(timeout=10)
    return conn_a, box["b"], tap, b


class TestFrameFuzz:
    def test_corrupt_ciphertext_frame_clean_error(self):
        rng = __import__("random").Random(1234)
        for trial in range(8):
            conn_a, conn_b, sock_a, sock_b = _good_pair()
            conn_a.write(b"hello")
            assert conn_b.read(5) == b"hello"
            # now inject a corrupted/garbage frame directly
            frame = bytearray(
                os.urandom(
                    sconn.TOTAL_FRAME_SIZE + sconn.AEAD_SIZE_OVERHEAD
                )
            )
            sock_a.sendall(bytes(frame))
            with pytest.raises(CLEAN):
                conn_b.read(1)
            sock_a.close()
            sock_b.close()

    def test_truncated_frame_clean_error(self):
        conn_a, conn_b, sock_a, sock_b = _good_pair()
        sock_a.sendall(b"\x01\x02\x03")  # partial frame then EOF
        sock_a.close()
        with pytest.raises(CLEAN):
            conn_b.read(1)
        sock_b.close()

    def test_replayed_frame_rejected(self):
        """Nonce discipline: replaying a captured valid frame must fail
        authentication (the counter moved on)."""
        conn_a, conn_b, sock_a, sock_b = _good_pair()
        captured = {}
        sock_a.on_send = lambda d: captured.setdefault("frame", d)
        conn_a.write(b"first")
        assert conn_b.read(5) == b"first"
        sock_a.on_send = None
        sock_a.sendall(captured["frame"])  # replay
        with pytest.raises(CLEAN):
            conn_b.read(1)
        sock_a.close()
        sock_b.close()
