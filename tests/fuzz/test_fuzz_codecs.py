"""Adversarial-input fuzzing for the hand-rolled codecs and admission
surfaces.

Model: reference test/fuzz README targets — mempool CheckTx, p2p
addrbook JSON, PEX Receive, and the jsonrpc server — plus the proto
codec families this framework hand-rolls (the reference gets these from
gogoproto codegen; hand-rolled decoders are exactly where adversarial
bytes bite). Property: random/garbage input must produce a CLEAN
rejection (ValueError/Exception subclass), never a hang, and structured
round-trips must be lossless. Bounded example counts keep this CI-fast.
"""

from __future__ import annotations

import json
import os

import pytest

pytest.importorskip("hypothesis", reason="hypothesis not installed")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

_FUZZ = settings(
    max_examples=80,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)

CLEAN = (ValueError, KeyError, IndexError, OverflowError, EOFError, TypeError)


def _expect_clean(fn, data):
    """Decoder contract under garbage: return something or raise CLEAN."""
    try:
        fn(data)
    except CLEAN:
        pass


class TestProtoCodecGarbage:
    @_FUZZ
    @given(st.binary(max_size=512))
    def test_consensus_messages(self, data):
        from cometbft_tpu.consensus.messages import decode_consensus_message

        _expect_clean(decode_consensus_message, data)

    @_FUZZ
    @given(st.binary(max_size=512))
    def test_blocksync_messages(self, data):
        from cometbft_tpu.blocksync.messages import decode_blocksync_message

        _expect_clean(decode_blocksync_message, data)

    @_FUZZ
    @given(st.binary(max_size=512))
    def test_statesync_messages(self, data):
        from cometbft_tpu.statesync.messages import decode_statesync_message

        _expect_clean(decode_statesync_message, data)

    @_FUZZ
    @given(st.binary(max_size=512))
    def test_mempool_txs_message(self, data):
        from cometbft_tpu.mempool.reactor import decode_txs_message

        _expect_clean(decode_txs_message, data)

    @_FUZZ
    @given(st.binary(max_size=512))
    def test_pex_messages(self, data):
        from cometbft_tpu.p2p.pex.reactor import decode_pex_message

        _expect_clean(decode_pex_message, data)

    @_FUZZ
    @given(st.binary(max_size=512))
    def test_evidence_decode(self, data):
        from cometbft_tpu.types.evidence import decode_evidence

        _expect_clean(decode_evidence, data)

    @_FUZZ
    @given(st.binary(max_size=512))
    def test_block_decode(self, data):
        from cometbft_tpu.types.block import Block

        _expect_clean(Block.decode, data)

    @_FUZZ
    @given(st.binary(max_size=256))
    def test_privval_message_decode(self, data):
        from cometbft_tpu.privval.socket import decode_privval_message

        _expect_clean(decode_privval_message, data)


class TestBlocksyncRoundtrip:
    @_FUZZ
    @given(st.integers(min_value=1, max_value=2**62))
    def test_block_request(self, height):
        from cometbft_tpu.blocksync.messages import (
            BlockRequest,
            decode_blocksync_message,
            encode_blocksync_message,
        )

        msg = decode_blocksync_message(encode_blocksync_message(BlockRequest(height=height)))
        assert isinstance(msg, BlockRequest) and msg.height == height


class TestMempoolCheckTxFuzz:
    def test_garbage_txs_never_crash_the_mempool(self):
        """Reference fuzz target mempool/v0 CheckTx: arbitrary tx bytes
        through the full mempool + kvstore app path."""
        from cometbft_tpu.abci.client import LocalClient
        from cometbft_tpu.abci.kvstore import KVStoreApplication
        from cometbft_tpu.config import MempoolConfig
        from cometbft_tpu.mempool import ErrMempoolIsFull, ErrTxInCache, ErrTxTooLarge
        from cometbft_tpu.mempool.clist_mempool import CListMempool

        client = LocalClient(KVStoreApplication())
        client.start()
        try:
            mp = CListMempool(MempoolConfig(), client, height=0)
            rng = __import__("random").Random(99)
            for _ in range(300):
                n = rng.randrange(0, 200)
                tx = bytes(rng.randrange(256) for _ in range(n))
                try:
                    mp.check_tx(tx)
                except (ErrTxInCache, ErrTxTooLarge, ErrMempoolIsFull, ValueError):
                    pass
            mp.flush_app_conn()
            assert mp.size() >= 0  # alive and consistent
        finally:
            client.stop()


class TestAddrbookJSONFuzz:
    @_FUZZ
    @given(
        st.one_of(
            st.binary(max_size=200),
            st.text(max_size=200).map(lambda s: s.encode()),
        )
    )
    def test_garbage_file_rejected_cleanly(self, blob):
        import tempfile

        from cometbft_tpu.p2p.pex.addrbook import AddrBook

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "addrbook.json")
            with open(path, "wb") as f:
                f.write(blob)
            book = AddrBook(path)
            try:
                book._load()
            except CLEAN + (json.JSONDecodeError,):
                pass

    def test_malformed_entries_skipped_or_rejected(self):
        import tempfile

        from cometbft_tpu.p2p.pex.addrbook import AddrBook

        docs = [
            {"key": "x", "addrs": [{"addr": {}}]},
            {"key": "x", "addrs": [{"addr": {"id": 5, "ip": [], "port": "x"}}]},
            {"addrs": "not-a-list"},
            {"key": None, "addrs": [None]},
        ]
        for doc in docs:
            with tempfile.TemporaryDirectory() as d:
                path = os.path.join(d, "addrbook.json")
                with open(path, "w") as f:
                    json.dump(doc, f)
                book = AddrBook(path)
                try:
                    book._load()
                except CLEAN:
                    pass


class TestJSONRPCServerFuzz:
    @pytest.fixture(scope="class")
    def server(self):
        """A live RPC server over a stub environment."""
        import threading

        from cometbft_tpu.libs.log import new_nop_logger
        from cometbft_tpu.rpc.server import RPCServer

        class _StubEnv:
            def health(self):
                return {}

            def status(self):
                return {"ok": True}

        srv = RPCServer(_StubEnv(), logger=new_nop_logger())
        srv.serve("127.0.0.1", 0)
        yield srv
        srv.stop()

    def _post(self, srv, body: bytes):
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.bound_port, timeout=10
        )
        try:
            conn.request(
                "POST", "/", body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def test_garbage_bodies(self, server):
        rng = __import__("random").Random(7)
        cases = [
            b"",
            b"{",
            b"null",
            b"[]",
            b'{"jsonrpc":"2.0"}',
            b'{"method": 5, "id": {}}',
            b'{"method":"status","params":"notadict","id":1}',
            b'{"method":"nosuch","id":1}',
            b'{"method":"status","id":[[[]]]}',
            json.dumps({"method": "status", "id": 1, "params": {"x" * 500: 1}}).encode(),
        ] + [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 64))) for _ in range(30)]
        for body in cases:
            status, payload = self._post(server, body)
            assert status in (200, 400, 500), (body, status)
            # the server must still answer a well-formed request after
        status, payload = self._post(
            server, b'{"jsonrpc":"2.0","method":"health","id":1}'
        )
        assert status == 200 and json.loads(payload)["result"] == {}

    def test_garbage_uri_routes(self, server):
        import http.client

        for path in (
            "/%00%ff", "/status?height=zzz", "/a" * 100,
            "/block?height=-9999999999999999999999",
            "/tx?hash=!!!", "/subscribe",
        ):
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.bound_port, timeout=10
            )
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                assert resp.status in (200, 400, 404, 500)
                resp.read()
            finally:
                conn.close()
        # alive after the abuse
        status, payload = self._post(
            server, b'{"jsonrpc":"2.0","method":"health","id":1}'
        )
        assert status == 200
