"""AOT shape-bucket precompilation (crypto/tpu/aot.py).

Pins the PR's acceptance contract: after a warm boot covering a bucket,
a real verify_batch dispatch at that bucket triggers ZERO new XLA
compilations (registry miss counter unchanged). Plus the degradation
paths: corrupt/truncated executable-store entries recompile fresh with
a warning, fingerprint changes invalidate instead of trusting stale
executables, stale kernel ids are never resolved to a live name, and a
mid-warmup stop() joins within one compile.

Toy kernels keep everything except the acceptance test off the
expensive ed25519 program.
"""

import glob
import os
import pickle
import threading
import time
import weakref

import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.tpu import aot, calibrate


def _toy_kernel():
    import jax

    @jax.jit
    def parity_kernel(rows):
        return (rows.sum(axis=0) % 2) == 0

    return parity_kernel


def _rows(bucket, seed=3):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 100, size=(3, bucket)).astype(np.int32)


@pytest.fixture
def no_store():
    """Registry tests that count compiles exactly: disable the disk
    executable store (conftest's .jax_cache would otherwise serve
    deserialized executables and skew the counts)."""
    aot.configure_exec_store("")
    yield
    aot.configure_exec_store(None)


@pytest.fixture
def tmp_store(tmp_path):
    root = str(tmp_path / "aot_exec")
    aot.configure_exec_store(root)
    yield root
    aot.configure_exec_store(None)


class TestStableKernelName:
    def test_registration_wins_and_pins(self):
        k = _toy_kernel()
        aot.register_kernel("test.toy_registered", k)
        assert aot.stable_kernel_name(k) == "test.toy_registered"
        # repeated: same answer, not a fresh serial
        assert aot.stable_kernel_name(k) == "test.toy_registered"

    def test_distinct_objects_same_qualname_get_serials(self):
        def make():
            def inner(x):
                return x

            return inner

        a, b = make(), make()
        na, nb = aot.stable_kernel_name(a), aot.stable_kernel_name(b)
        assert na != nb
        assert nb.startswith(na.split("#")[0])

    def test_id_reuse_after_gc_is_detected_not_trusted(self):
        """The id()-keyed bug this module fixes: a NEW object occupying
        a dead kernel's id must get a fresh name, never the dead one's
        (which would run the wrong executable)."""

        def make():
            def victim(x):
                return x

            return victim

        old = make()
        old_name = aot.stable_kernel_name(old)
        new = make()
        # simulate CPython id reuse: bind the dead kernel's name to the
        # new object's id, liveness-guarded by a weakref about to die
        with aot._name_mtx:
            aot._name_by_id[id(new)] = (old_name, weakref.ref(old), None)
        del old
        import gc

        gc.collect()
        assert aot.stable_kernel_name(new) != old_name


class TestExecutableRegistry:
    def test_miss_compiles_hit_reuses_and_runs_right(self, no_store):
        import jax

        reg = aot.ExecutableRegistry()
        k = _toy_kernel()
        rows = _rows(64)
        placed = [jax.device_put(rows, jax.devices("cpu")[0])]
        out1 = np.asarray(reg.call(k, placed))
        assert (out1 == ((rows.sum(axis=0) % 2) == 0)).all()
        s = reg.stats()
        assert (s["misses"], s["hits"], s["compiles"]) == (1, 0, 1)
        out2 = np.asarray(reg.call(k, [jax.device_put(rows, jax.devices("cpu")[0])]))
        assert (out2 == out1).all()
        s = reg.stats()
        assert (s["misses"], s["hits"], s["compiles"]) == (1, 1, 1)
        # a different bucket is a different executable
        reg.warm(k, [((3, 128), np.int32)])
        assert reg.compile_count == 2

    def test_lru_bound_evicts_and_recompiles(self, no_store):
        reg = aot.ExecutableRegistry(max_entries=2)
        k = _toy_kernel()
        for bucket in (64, 128, 256):
            reg.warm(k, [((3, bucket), np.int32)])
        assert len(reg) == 2
        assert reg.metrics.evictions.value() == 1
        assert reg.compile_count == 3
        # 64 was evicted (LRU) → warming it again is a real compile
        assert reg.warm(k, [((3, 64), np.int32)]) > 0.0
        assert reg.compile_count == 4

    def test_fingerprint_change_invalidates_never_trusts(
        self, no_store, monkeypatch
    ):
        reg = aot.ExecutableRegistry()
        k = _toy_kernel()
        reg.warm(k, [((3, 64), np.int32)])
        assert len(reg) == 1 and reg.compile_count == 1
        monkeypatch.setattr(
            aot, "backend_fingerprint", lambda: "other-jax:tpu:v9:8"
        )
        # the entry compiled against the old backend is discarded and
        # the same (kernel, bucket) recompiles under the new fingerprint
        assert reg.warm(k, [((3, 64), np.int32)]) > 0.0
        assert reg.compile_count == 2
        assert reg.metrics.invalidations.value() == 1
        assert len(reg) == 1

    def test_racing_misses_compile_once(self, no_store):
        reg = aot.ExecutableRegistry()
        k = _toy_kernel()
        orig = reg._build
        started = threading.Event()

        def slow_build(*a, **kw):
            started.set()
            time.sleep(0.3)
            return orig(*a, **kw)

        reg._build = slow_build
        outs = []

        def worker():
            outs.append(reg.warm(k, [((3, 64), np.int32)]))

        ts = [threading.Thread(target=worker) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        # one leader compiles; the others either followed the in-flight
        # build or (if scheduled late) hit the finished entry — never a
        # second compile of the same key
        assert reg.compile_count == 1
        assert reg.metrics.registry_misses.value() >= 1

    def test_compile_failure_retries_fresh_once(self, no_store):
        reg = aot.ExecutableRegistry()
        k = _toy_kernel()
        orig = reg._build
        calls = []

        def flaky(*a, **kw):
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("truncated persistent cache entry")
            return orig(*a, **kw)

        reg._build = flaky
        with pytest.warns(RuntimeWarning, match="retrying with a fresh"):
            secs = reg.warm(k, [((3, 64), np.int32)])
        assert secs > 0.0
        assert len(calls) == 2
        assert reg.metrics.compile_fallbacks.value() == 1


class TestExecutableStore:
    def test_second_registry_loads_without_compiling(self, tmp_store):
        k = _toy_kernel()
        reg1 = aot.ExecutableRegistry()
        reg1.warm(k, [((3, 64), np.int32)])
        assert reg1.compile_count == 1
        assert glob.glob(os.path.join(tmp_store, "*.aotexe"))
        # a fresh registry (new process boot) deserializes — no trace,
        # no lower, no compile
        reg2 = aot.ExecutableRegistry()
        assert reg2.warm(k, [((3, 64), np.int32)]) == 0.0
        assert reg2.compile_count == 0
        assert reg2.metrics.exec_store_hits.value() == 1
        # and the loaded executable actually runs correctly
        import jax

        rows = _rows(64)
        out = np.asarray(
            reg2.call(k, [jax.device_put(rows, jax.devices("cpu")[0])])
        )
        assert (out == ((rows.sum(axis=0) % 2) == 0)).all()

    def test_corrupt_entry_warns_and_recompiles(self, tmp_store):
        k = _toy_kernel()
        aot.ExecutableRegistry().warm(k, [((3, 64), np.int32)])
        (path,) = glob.glob(os.path.join(tmp_store, "*.aotexe"))
        with open(path, "wb") as fh:
            fh.write(b"\x00garbage not a pickle")
        reg = aot.ExecutableRegistry()
        with pytest.warns(RuntimeWarning, match="unreadable"):
            secs = reg.warm(k, [((3, 64), np.int32)])
        assert secs > 0.0 and reg.compile_count == 1
        # the corrupt file was discarded and replaced by the fresh build
        (path2,) = glob.glob(os.path.join(tmp_store, "*.aotexe"))
        with open(path2, "rb") as fh:
            assert fh.read(20) != b"\x00garbage not a pick"

    def test_truncated_entry_warns_and_recompiles(self, tmp_store):
        k = _toy_kernel()
        aot.ExecutableRegistry().warm(k, [((3, 64), np.int32)])
        (path,) = glob.glob(os.path.join(tmp_store, "*.aotexe"))
        blob = open(path, "rb").read()
        with open(path, "wb") as fh:
            fh.write(blob[: len(blob) // 3])
        reg = aot.ExecutableRegistry()
        with pytest.warns(RuntimeWarning, match="unreadable"):
            assert reg.warm(k, [((3, 64), np.int32)]) > 0.0
        assert reg.compile_count == 1

    def test_wrong_structure_entry_warns_and_recompiles(self, tmp_store):
        k = _toy_kernel()
        aot.ExecutableRegistry().warm(k, [((3, 64), np.int32)])
        (path,) = glob.glob(os.path.join(tmp_store, "*.aotexe"))
        with open(path, "wb") as fh:
            # valid pickle, not a serialized executable triple
            pickle.dump((b"payload", "in_tree", "out_tree"), fh)
        reg = aot.ExecutableRegistry()
        with pytest.warns(RuntimeWarning, match="failed to\\s+deserialize"):
            assert reg.warm(k, [((3, 64), np.int32)]) > 0.0
        assert reg.compile_count == 1


class TestBucketLadder:
    def test_p50_first_cap_last_subfloor_reversed(self, monkeypatch):
        monkeypatch.setattr(calibrate, "compile_seconds", lambda *a: {})
        assert aot.bucket_ladder(floor=1024, cap=8192) == [
            1024, 2048, 4096, 8192, 512, 256, 128, 64,
        ]

    def test_measured_compile_cost_reorders_above_floor(self, monkeypatch):
        monkeypatch.setattr(
            calibrate,
            "compile_seconds",
            lambda *a: {4096: 0.1, 2048: 5.0},
        )
        # cheap measured buckets warm first; unmeasured 8192 keys by
        # size and stays last
        assert aot.bucket_ladder(floor=1024, cap=8192) == [
            1024, 4096, 2048, 8192, 512, 256, 128, 64,
        ]

    def test_floor_above_cap_clamps(self, monkeypatch):
        monkeypatch.setattr(calibrate, "compile_seconds", lambda *a: {})
        ladder = aot.bucket_ladder(floor=100_000, cap=256)
        assert ladder[0] == 256
        assert sorted(ladder) == [64, 128, 256]


class TestWarmBootLifecycle:
    @pytest.fixture(autouse=True)
    def _clean_handle(self):
        yield
        aot.stop_warm_boot(timeout=5.0)

    def test_stop_mid_warmup_joins_within_bound(self):
        compiling = threading.Event()

        def body(stop_event):
            # a warm boot that would take ~5 s unless stopped between
            # "compiles" (the run_warm_boot contract)
            for _ in range(100):
                compiling.set()
                if stop_event.is_set():
                    return "stopped"
                time.sleep(0.05)
            return "ran dry"

        wb = aot.start_warm_boot("background", body=body)
        assert aot.current_warm_boot() is wb
        assert compiling.wait(5)
        t0 = time.perf_counter()
        assert aot.stop_warm_boot(timeout=5.0) is True
        assert time.perf_counter() - t0 < 2.0
        assert wb.result == "stopped"
        assert aot.current_warm_boot() is None

    def test_pre_set_stop_event_warms_nothing(self):
        reg = aot.ExecutableRegistry()
        stop = threading.Event()
        stop.set()
        obs = aot.run_warm_boot(
            sizes=[64], registry=reg, stop_event=stop
        )
        assert obs == []
        assert reg.compile_count == 0
        assert reg.metrics.warmup_state.value() == 3  # stopped

    def test_eager_swallows_body_failure(self):
        def body(stop_event):
            raise RuntimeError("device plane down")

        wb = aot.start_warm_boot("eager", body=body)
        assert wb.done
        assert isinstance(wb.error, RuntimeError)

    def test_off_is_a_noop(self):
        aot.stop_warm_boot()
        assert aot.start_warm_boot("off") is None
        assert aot.current_warm_boot() is None

    def test_background_result_and_join(self):
        wb = aot.start_warm_boot("background", body=lambda stop: 42)
        assert wb.join(timeout=5.0) is True
        assert wb.result == 42 and wb.error is None

    def test_supervisor_canary_joins_warm_boot(self):
        """The supervisor's warmup canary must not probe (and declare
        HEALTHY) until the warm boot finishes or the watchdog bound
        expires."""
        from cometbft_tpu.crypto.batch import BackendSpec
        from cometbft_tpu.crypto.supervisor import BackendSupervisor

        release = threading.Event()
        order = []

        def body(stop_event):
            release.wait(10)
            order.append("warm")

        wb = aot.start_warm_boot("background", body=body)
        sup = BackendSupervisor(
            spec=BackendSpec("cpu"), dispatch_timeout_ms=30_000
        )
        probed = threading.Event()
        orig = sup.probe_now

        def probe_spy(*a, **kw):
            order.append("probe")
            probed.set()
            return orig(*a, **kw)

        sup.probe_now = probe_spy
        try:
            sup.warmup_canary()
            assert not probed.wait(0.5)  # still joined on the warm boot
            release.set()
            assert probed.wait(10)
            assert order == ["warm", "probe"]
            assert wb.done
        finally:
            release.set()
            sup.stop()


class TestWarmBootMode:
    def test_env_beats_config_beats_default(self, monkeypatch):
        monkeypatch.delenv("CBFT_WARM_BOOT", raising=False)
        monkeypatch.delenv("CBFT_TPU_WARMUP", raising=False)
        assert aot.warm_boot_mode() == "background"
        assert aot.warm_boot_mode("eager") == "eager"
        monkeypatch.setenv("CBFT_WARM_BOOT", "off")
        assert aot.warm_boot_mode("eager") == "off"

    def test_legacy_kill_switch_forces_off(self, monkeypatch):
        monkeypatch.setenv("CBFT_TPU_WARMUP", "0")
        monkeypatch.setenv("CBFT_WARM_BOOT", "eager")
        assert aot.warm_boot_mode("background") == "off"

    def test_invalid_mode_rejected(self, monkeypatch):
        monkeypatch.delenv("CBFT_TPU_WARMUP", raising=False)
        monkeypatch.setenv("CBFT_WARM_BOOT", "sideways")
        with pytest.raises(ValueError, match="warm_boot"):
            aot.warm_boot_mode()

    def test_config_validate_rejects_bad_value(self):
        from cometbft_tpu.config import Config

        cfg = Config()
        assert cfg.crypto.warm_boot == "background"
        cfg.crypto.warm_boot = "sideways"
        with pytest.raises(ValueError, match="warm_boot"):
            cfg.validate_basic()


class TestCompileCalibration:
    @pytest.fixture
    def table(self, tmp_path, monkeypatch):
        monkeypatch.delenv("CBFT_TPU_CALIBRATION", raising=False)
        path = str(tmp_path / "tpu_calibration.json")
        calibrate.set_table_path(path)
        yield path
        calibrate.set_table_path(None)

    def test_merge_and_read_back_per_topology(self, table):
        obs = [
            {"kernel": "k", "bucket": 64, "sharded": True,
             "topology": "cpu:8", "compile_s": 1.25, "cached": False},
            {"kernel": "k2", "bucket": 64, "sharded": False,
             "topology": "cpu:8", "compile_s": 0.75, "cached": False},
            {"kernel": "k", "bucket": 128, "sharded": True,
             "topology": "cpu:8", "compile_s": 3.0, "cached": False},
            # cached observations measure the cache, not the compiler
            {"kernel": "k", "bucket": 256, "sharded": True,
             "topology": "cpu:8", "compile_s": 0.0, "cached": True},
        ]
        assert calibrate.merge_compile_times(obs) is not None
        got = calibrate.compile_seconds("cpu:8")
        assert got == {64: 2.0, 128: 3.0}
        assert calibrate.compile_seconds("tpu:64") == {}

    def test_min_compile_secs_tracks_cheapest_observation(self, table):
        assert calibrate.persistent_cache_min_compile_secs() == 5.0
        calibrate.merge_compile_times([
            {"kernel": "k", "bucket": 64, "sharded": True,
             "topology": "cpu:8", "compile_s": 1.2, "cached": False},
        ])
        # half the cheapest compile: every warm-boot build is admitted
        assert calibrate.persistent_cache_min_compile_secs() == pytest.approx(
            0.6
        )

    def test_min_compile_secs_floors_at_point_one(self, table):
        calibrate.merge_compile_times([
            {"kernel": "k", "bucket": 64, "sharded": False,
             "topology": "cpu:8", "compile_s": 0.05, "cached": False},
        ])
        assert calibrate.persistent_cache_min_compile_secs() == 0.1


class TestZeroCompileDispatch:
    """The PR acceptance contract, end to end on the real ed25519
    kernels and the 8-device virtual mesh."""

    def test_warmed_bucket_dispatches_with_zero_new_compiles(self):
        from cometbft_tpu.crypto.tpu import ed25519_batch, mesh

        assert mesh.n_devices() == 8
        # warm the 64 bucket (sharded — what 8-device dispatch runs)
        obs = aot.run_warm_boot(sizes=[64], include_single=False)
        assert obs and all(ob["topology"] for ob in obs)
        reg = aot.default_registry()
        compiles = reg.compile_count
        misses = reg.metrics.registry_misses.value()
        hits = reg.metrics.registry_hits.value()

        keys = [ed.gen_priv_key_from_secret(bytes([i, 99])) for i in range(40)]
        pks, msgs, sigs = [], [], []
        for i, k in enumerate(keys):
            m = b"warm dispatch %d" % i
            s = bytearray(k.sign(m))
            if i % 7 == 0:
                s[3] ^= 1
            pks.append(k.pub_key().bytes())
            msgs.append(m)
            sigs.append(bytes(s))
        got = ed25519_batch.verify_batch(pks, msgs, sigs)  # 40 → pad 64
        want = [
            ed.PubKeyEd25519(p).verify_signature(m, s)
            for p, m, s in zip(pks, msgs, sigs)
        ]
        assert got == want
        # the dispatch was a pure registry hit: no new executable, no
        # new miss — nothing on the hot path paid trace+compile
        assert reg.compile_count == compiles
        assert reg.metrics.registry_misses.value() == misses
        assert reg.metrics.registry_hits.value() > hits

    def test_single_device_variant_also_warms_to_a_hit(self):
        import jax

        from cometbft_tpu.crypto.tpu import ed25519_batch

        # the degraded-to-one-device fallback variant is part of the
        # default plan (include_single); a lookup at the warmed bucket
        # must be a hit too
        aot.run_warm_boot(sizes=[64], include_single=True)
        reg = aot.default_registry()
        compiles = reg.compile_count
        misses = reg.metrics.registry_misses.value()
        reg.lookup(
            ed25519_batch.verify_kernel,
            [jax.ShapeDtypeStruct((32, 64), np.uint32)],
            sharded=False,
        )
        assert reg.compile_count == compiles
        assert reg.metrics.registry_misses.value() == misses
