"""QoS admission control (crypto/qos.py + crypto/scheduler.py).

Contract under test:
  - spec grammar: ``default`` ladder, ``off`` FIFO, custom
    ``name[:policy[:max_queue[:weight]]]`` lists; malformed specs fail
    in the [crypto]-knob validation style (config.validate_basic);
  - class resolution: subsystem tags map to lanes, untagged/unknown
    traffic to the TOP class, aliases fold in;
  - flush assembly: the top class drains strictly first, the classes
    below share the remaining budget by weighted deficit round-robin;
  - overload policies at the class bound: block (bounded backpressure,
    then inline CPU), shed (deadline, then inline CPU), drop (immediate
    ``rejected`` verdict) — exact verdicts on every path;
  - per-tenant token-bucket quotas (block classes counted, never
    throttled);
  - brownout: burn/supervisor-state evidence disables sheddable classes
    lowest-first, hysteretic re-admission, verify_qos_* counters;
  - N submitters racing stop() on a full queue leak no futures;
  - the chaos overload rung end to end (tools/chaos.py --overload).
"""

import threading
import time

import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto import qos as qoslib
from cometbft_tpu.crypto.qos import (
    BrownoutController,
    QoSMetrics,
    TenantQuotas,
    TokenBucket,
    parse_qos_classes,
    resolve_class,
)
from cometbft_tpu.crypto.scheduler import VerifyScheduler
from cometbft_tpu.libs.metrics import Registry


def _make_items(n, tag=b"", poison_at=None):
    items = []
    for i in range(n):
        k = ed.gen_priv_key_from_secret(
            b"qos-" + tag + bytes([i & 0xFF, i >> 8])
        )
        msg = b"qos-msg-" + tag + i.to_bytes(4, "big")
        sig = k.sign(msg)
        if poison_at is not None and i == poison_at:
            sig = b"\x00" * 64
        items.append((k.pub_key(), msg, sig))
    return items


@pytest.fixture(autouse=True)
def _clean_qos_env(monkeypatch):
    for var in (
        "CBFT_QOS_CLASSES",
        "CBFT_QOS_SHED_MS",
        "CBFT_QOS_TENANT_RATE",
        "CBFT_SUBMIT_TIMEOUT_MS",
    ):
        monkeypatch.delenv(var, raising=False)


class TestSpecParsing:
    def test_default_ladder(self):
        specs = parse_qos_classes("default")
        assert [s.name for s in specs] == list(qoslib.CLASS_ORDER)
        assert {s.name: s.policy for s in specs} == qoslib.DEFAULT_POLICIES
        assert {s.name: s.weight for s in specs} == qoslib.DEFAULT_WEIGHTS
        assert all(s.max_queue is None for s in specs)

    def test_empty_and_none_mean_default(self):
        assert parse_qos_classes("") == parse_qos_classes("default")
        assert parse_qos_classes(None) == parse_qos_classes("default")

    def test_off_disables(self):
        assert parse_qos_classes("off") is None
        assert parse_qos_classes("  OFF ") is None

    def test_custom_spec(self):
        specs = parse_qos_classes(
            "consensus,blocksync:shed:8192:4,mempool:drop"
        )
        assert [s.name for s in specs] == ["consensus", "blocksync", "mempool"]
        bs = specs[1]
        assert (bs.policy, bs.max_queue, bs.weight) == ("shed", 8192, 4)
        # omitted fields inherit the defaults
        assert specs[0].policy == "block"
        assert specs[2].weight == qoslib.DEFAULT_WEIGHTS["mempool"]

    def test_unknown_class_rejected(self):
        with pytest.raises(ValueError, match="unknown class 'gossip'"):
            parse_qos_classes("consensus,gossip")

    def test_duplicate_rejected(self):
        with pytest.raises(ValueError, match="listed twice"):
            parse_qos_classes("consensus,consensus")

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="policy must be one of"):
            parse_qos_classes("mempool:yeet")

    def test_nonpositive_bound_rejected(self):
        with pytest.raises(ValueError, match="max_queue must be a positive"):
            parse_qos_classes("blocksync:shed:0")
        with pytest.raises(ValueError, match="max_queue must be a positive"):
            parse_qos_classes("blocksync:shed:nope")

    def test_nonpositive_weight_rejected(self):
        with pytest.raises(ValueError, match="weight must be a positive"):
            parse_qos_classes("blocksync:shed:64:-2")

    def test_too_many_fields_rejected(self):
        with pytest.raises(ValueError, match="too many fields"):
            parse_qos_classes("blocksync:shed:64:2:extra")

    def test_only_commas_rejected(self):
        with pytest.raises(ValueError, match="no classes specified"):
            parse_qos_classes(",,")

    def test_non_string_rejected(self):
        with pytest.raises(ValueError, match="must be a string"):
            parse_qos_classes(5)

    def test_shed_ms_env_override(self, monkeypatch):
        monkeypatch.setenv("CBFT_QOS_SHED_MS", "7")
        specs = parse_qos_classes("default")
        assert all(s.shed_ms == 7 for s in specs)


class TestClassResolution:
    NAMES = qoslib.CLASS_ORDER

    def test_tagged(self):
        for name in self.NAMES:
            assert resolve_class(name, self.NAMES) == name

    def test_untagged_and_unknown_go_top(self):
        assert resolve_class(None, self.NAMES) == "consensus"
        assert resolve_class("", self.NAMES) == "consensus"
        assert resolve_class("something-new", self.NAMES) == "consensus"

    def test_aliases(self):
        assert resolve_class("statesync", self.NAMES) == "light"
        assert resolve_class("rpc", self.NAMES) == "light"

    def test_alias_without_configured_target_goes_top(self):
        names = ("consensus", "mempool")
        assert resolve_class("statesync", names) == "consensus"


class TestConfigValidation:
    def test_default_config_validates(self):
        from cometbft_tpu.config import Config

        Config().validate_basic()

    def test_bad_qos_classes_rejected(self):
        from cometbft_tpu.config import Config

        cfg = Config()
        cfg.crypto.qos_classes = "consensus,gossip"
        with pytest.raises(ValueError, match="crypto.qos_classes"):
            cfg.validate_basic()

    def test_bad_tenant_rate_rejected(self):
        from cometbft_tpu.config import Config

        cfg = Config()
        cfg.crypto.qos_tenant_rate = -1
        with pytest.raises(ValueError, match="crypto.qos_tenant_rate"):
            cfg.validate_basic()
        cfg.crypto.qos_tenant_rate = True
        with pytest.raises(ValueError, match="crypto.qos_tenant_rate"):
            cfg.validate_basic()

    def test_off_and_custom_validate(self):
        from cometbft_tpu.config import Config

        cfg = Config()
        cfg.crypto.qos_classes = "off"
        cfg.validate_basic()
        cfg.crypto.qos_classes = "consensus,mempool:drop:256"
        cfg.validate_basic()


class TestFlushAssembly:
    """Drain order on a live scheduler whose worker is parked on a long
    deadline flush (10s flush_us, huge budget): submits land in lanes,
    the test assembles batches directly under the lock."""

    def _sched(self):
        s = VerifyScheduler(
            spec="cpu", flush_us=10_000_000, lane_budget=100_000,
            qos="default",
        )
        s.start()
        return s

    def test_top_class_strictly_first_then_weighted_shares(self):
        s = self._sched()
        try:
            futs = []
            for sub, n_reqs in (
                ("consensus", 2), ("evidence", 1),
                ("blocksync", 4), ("mempool", 4),
            ):
                for i in range(n_reqs):
                    futs.append(s.submit(
                        _make_items(4, tag=sub.encode() + bytes([i])),
                        subsystem=sub,
                    ))
            with s._cond:
                batch = s._assemble_locked(12, unbounded=False)
            # 12-sig budget: both consensus requests (strict), then
            # evidence (weight 4 outranks the rest in round one)
            assert [r.qclass for r in batch] == [
                "consensus", "consensus", "evidence"
            ]
            with s._cond:
                # unspent deficit carries across flushes while a lane is
                # backlogged (that is the DRR contract); zero it here to
                # observe the pure weighted share
                for lane in s._lanes.values():
                    lane.deficit = 0
                batch2 = s._assemble_locked(12, unbounded=False)
            # blocksync (weight 2) : mempool (weight 1) share 12 sigs 2:1
            counts = {}
            for r in batch2:
                counts[r.qclass] = counts.get(r.qclass, 0) + 1
            assert counts == {"blocksync": 2, "mempool": 1}
            # hand-assembled requests still need verdicts: dispatch them
            s._dispatch(batch + batch2, "explicit")
        finally:
            s.stop()
        for f in futs:
            ok, mask = f.result(timeout=10)
            assert ok and all(mask)

    def test_unbounded_drain_takes_everything_in_priority_order(self):
        s = self._sched()
        try:
            for sub in ("mempool", "blocksync", "consensus"):
                s.submit(_make_items(4, tag=sub.encode()), subsystem=sub)
            with s._cond:
                batch = s._assemble_locked(1, unbounded=True)
            assert [r.qclass for r in batch] == [
                "consensus", "blocksync", "mempool"
            ]
            s._dispatch(batch, "explicit")
        finally:
            s.stop()

    def test_oversize_request_still_dispatches(self):
        s = self._sched()
        try:
            f = s.submit(_make_items(32), subsystem="consensus")
            with s._cond:
                batch = s._assemble_locked(4, unbounded=False)
            assert len(batch) == 1 and len(batch[0].items) == 32
            s._dispatch(batch, "explicit")
            ok, mask = f.result(timeout=10)
            assert ok and all(mask)
        finally:
            s.stop()


class TestOverloadPolicies:
    """Per-class behavior at the lane bound. flush_us is huge and the
    budget enormous, so the worker never drains mid-test: the second
    8-sig submit into an 8-sig lane hits the bound deterministically."""

    SPEC = "consensus:block:8,blocksync:shed:8,mempool:drop:8"

    def _sched(self, **kw):
        kw.setdefault("spec", "cpu")
        kw.setdefault("flush_us", 10_000_000)
        kw.setdefault("lane_budget", 100_000)
        kw.setdefault("qos", self.SPEC)
        s = VerifyScheduler(**kw)
        s.start()
        return s

    def test_block_times_out_to_inline_cpu(self):
        s = self._sched(submit_timeout_ms=80)
        try:
            s.submit(_make_items(8, tag=b"c0"), subsystem="consensus")
            t0 = time.monotonic()
            f = s.submit(
                _make_items(8, tag=b"c1", poison_at=3),
                subsystem="consensus",
            )
            waited = time.monotonic() - t0
            # the future is complete on return (inline CPU verdicts)
            ok, mask = f.result(timeout=0)
            assert waited >= 0.08
            assert not ok and mask == [
                True, True, True, False, True, True, True, True
            ]
            assert not f.rejected
            assert s.metrics.backpressure_timeouts.value() == 1
        finally:
            s.stop()

    def test_shed_waits_deadline_then_inline_cpu(self, monkeypatch):
        monkeypatch.setenv("CBFT_QOS_SHED_MS", "30")
        s = self._sched()
        try:
            s.submit(_make_items(8, tag=b"b0"), subsystem="blocksync")
            t0 = time.monotonic()
            f = s.submit(
                _make_items(8, tag=b"b1", poison_at=5),
                subsystem="blocksync",
            )
            waited = time.monotonic() - t0
            ok, mask = f.result(timeout=0)
            assert 0.03 <= waited < 5.0
            assert not ok and mask.count(False) == 1 and not mask[5]
            assert not f.rejected
            snap = s.queue_snapshot()["qos"]["classes"]["blocksync"]
            assert snap["sheds"] == 1
        finally:
            s.stop()

    def test_drop_rejects_immediately(self):
        s = self._sched()
        try:
            s.submit(_make_items(8, tag=b"m0"), subsystem="mempool")
            t0 = time.monotonic()
            f = s.submit(_make_items(8, tag=b"m1"), subsystem="mempool")
            waited = time.monotonic() - t0
            ok, mask = f.result(timeout=0)
            assert waited < 0.02  # no deadline wait on the drop path
            assert f.rejected
            assert not ok and mask == [False] * 8
            snap = s.queue_snapshot()["qos"]["classes"]["mempool"]
            assert snap["drops"] == 1
        finally:
            s.stop()

    def test_empty_lane_admits_oversize(self):
        # an empty lane always admits, even past the bound: one oversize
        # request still has to verify somewhere
        s = self._sched()
        try:
            f = s.submit(_make_items(20, tag=b"big"), subsystem="mempool")
            assert not f.done()
            snap = s.queue_snapshot()["qos"]["classes"]["mempool"]
            assert snap["depth"] == 1 and snap["pending_sigs"] == 20
        finally:
            s.stop()
        ok, mask = f.result(timeout=10)
        assert ok and all(mask)


class TestTenantQuotas:
    def test_token_bucket_refill(self):
        t = [0.0]
        b = TokenBucket(rate=10, burst=10, clock=lambda: t[0])
        assert b.try_take(10)
        assert not b.try_take(1)
        t[0] = 0.5  # 5 tokens back
        assert b.try_take(5)
        assert not b.try_take(1)

    def test_zero_rate_is_unlimited(self):
        q = TenantQuotas(rate=0)
        assert not q.enabled
        assert q.try_take("anyone", 10**9)

    def test_tenants_are_independent(self):
        t = [0.0]
        q = TenantQuotas(rate=4, burst=4, clock=lambda: t[0])
        assert q.try_take("blocksync", 4)
        assert not q.try_take("blocksync", 1)
        assert q.try_take("light", 4)  # a different bucket

    def test_scheduler_sheds_over_quota_tenant(self):
        # burst = 2x rate: the first 16-sig submit drains the bucket,
        # the second sheds (inline CPU, exact verdicts, counted)
        s = VerifyScheduler(
            spec="cpu", flush_us=10_000_000, lane_budget=100_000,
            qos="default", tenant_rate=8,
        )
        s.start()
        try:
            f1 = s.submit(_make_items(16, tag=b"q0"), subsystem="blocksync")
            assert not f1.done()
            f2 = s.submit(
                _make_items(16, tag=b"q1", poison_at=7),
                subsystem="blocksync",
            )
            ok, mask = f2.result(timeout=0)
            assert not ok and mask.count(False) == 1
            cls = s.queue_snapshot()["qos"]["classes"]["blocksync"]
            assert cls["quota_rejections"] == 1
            assert cls["sheds"] == 1
        finally:
            s.stop()
        ok, mask = f1.result(timeout=10)
        assert ok and all(mask)

    def test_block_class_counted_but_never_throttled(self):
        s = VerifyScheduler(
            spec="cpu", flush_us=10_000_000, lane_budget=100_000,
            qos="default", tenant_rate=8,
        )
        s.start()
        try:
            s.submit(_make_items(16, tag=b"cq0"), subsystem="consensus")
            f = s.submit(_make_items(16, tag=b"cq1"), subsystem="consensus")
            # over quota, still admitted to the lane (not completed)
            assert not f.done()
            cls = s.queue_snapshot()["qos"]["classes"]["consensus"]
            assert cls["quota_rejections"] == 1
            assert cls["admits"] == 2
            assert cls["sheds"] == 0 and cls["drops"] == 0
        finally:
            s.stop()
        ok, mask = f.result(timeout=10)
        assert ok and all(mask)


class TestBrownout:
    def test_ladder_trips_lowest_first_and_readmits_in_reverse(self):
        t = [0.0]
        changes = []
        bo = BrownoutController(
            ["mempool", "light", "blocksync"],
            clock=lambda: t[0],
            on_change=lambda cls, dis: changes.append((cls, dis)),
        )
        for expect in (["mempool"], ["mempool", "light"],
                       ["mempool", "light", "blocksync"]):
            t[0] += 0.3  # past the step cooldown
            bo.observe_burn(5.0)
            assert bo.disabled() == expect
        # a fourth overload observation has nowhere left to go
        t[0] += 0.3
        bo.observe_burn(5.0)
        assert bo.trips == 3
        assert not bo.allows("mempool")
        # re-admission: 3 clean observations per step, last disabled
        # comes back first
        for expect in (["mempool", "light"], ["mempool"], []):
            for _ in range(3):
                t[0] += 0.3
                bo.observe_burn(0.0)
            assert bo.disabled() == expect
        assert bo.readmissions == 3
        assert changes == [
            ("mempool", True), ("light", True), ("blocksync", True),
            ("blocksync", False), ("light", False), ("mempool", False),
        ]

    def test_hysteresis_band_holds(self):
        t = [0.0]
        bo = BrownoutController(["mempool"], clock=lambda: t[0])
        t[0] += 0.3
        bo.observe_burn(5.0)
        assert bo.disabled() == ["mempool"]
        # burn between clear (1.0) and trip (2.0): no re-admission ever
        for _ in range(20):
            t[0] += 0.3
            bo.observe_burn(1.5)
        assert bo.disabled() == ["mempool"]
        # one clean scrape is not enough (streak resets in the band)
        t[0] += 0.3
        bo.observe_burn(0.0)
        t[0] += 0.3
        bo.observe_burn(1.5)
        t[0] += 0.3
        bo.observe_burn(0.0)
        assert bo.disabled() == ["mempool"]

    def test_supervisor_state_trips(self):
        t = [0.3]
        bo = BrownoutController(["mempool"], clock=lambda: t[0])
        bo.observe_state("degraded")
        assert bo.disabled() == ["mempool"]
        # healthy alone does not re-admit until the streak accumulates
        for _ in range(3):
            t[0] += 0.3
            bo.observe_state("healthy")
        assert bo.disabled() == []

    def test_scheduler_brownout_applies_policies(self):
        reg = Registry()
        s = VerifyScheduler(
            spec="cpu", flush_us=10_000_000, lane_budget=100_000,
            qos="default", qos_metrics=QoSMetrics(reg),
        )
        s.start()
        try:
            # drive burn straight through the hub-watcher entry point:
            # brownout steps once per cooldown window
            deadline = time.monotonic() + 10.0
            while (
                len(s.brownout.disabled()) < 3
                and time.monotonic() < deadline
            ):
                s.on_burn(100.0)
                time.sleep(0.05)
            assert s.brownout.disabled() == [
                "mempool", "light", "blocksync"
            ]
            snap = s.queue_snapshot()["qos"]
            assert snap["classes"]["mempool"]["browned_out"]
            assert not snap["classes"]["consensus"]["browned_out"]
            # browned-out mempool drops, browned-out blocksync sheds,
            # consensus admits untouched
            fm = s.submit(_make_items(4, tag=b"bo-m"), subsystem="mempool")
            assert fm.rejected and fm.result(timeout=0)[0] is False
            fb = s.submit(
                _make_items(4, tag=b"bo-b"), subsystem="blocksync"
            )
            ok, mask = fb.result(timeout=0)  # shed inline, exact verdicts
            assert ok and all(mask) and not fb.rejected
            fc = s.submit(_make_items(4, tag=b"bo-c"), subsystem="consensus")
            assert not fc.done()
            # recovery: clean burn re-admits everything, bottom-up
            deadline = time.monotonic() + 10.0
            while s.brownout.disabled() and time.monotonic() < deadline:
                s.on_burn(0.0)
                time.sleep(0.05)
            assert s.brownout.disabled() == []
            bo = s.queue_snapshot()["qos"]["brownout"]
            assert bo["trips"] == 3 and bo["readmissions"] == 3
        finally:
            s.stop()
        assert fc.result(timeout=10)[0]
        # the verify_qos_* trip/readmit counters moved
        text = reg.expose()
        assert 'cometbft_verify_qos_brownouts{qclass="mempool"} 1' in text
        assert 'cometbft_verify_qos_readmits{qclass="mempool"} 1' in text

    def test_supervisor_state_listener_path(self):
        s = VerifyScheduler(
            spec="cpu", flush_us=10_000_000, qos="default",
        )
        s.on_supervisor_state("degraded")
        assert s.brownout.disabled() == ["mempool"]


class TestQoSMetricsConformance:
    """Every verify_qos_* series the admission layer touches must be
    well-formed Prometheus exposition under the cometbft namespace."""

    def _parse(self, text):
        series = {}
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            name_labels, value = line.rsplit(" ", 1)
            float(value)  # parses
            series[name_labels] = float(value)
        return series

    def test_touched_series_expose_cleanly(self, monkeypatch):
        monkeypatch.setenv("CBFT_QOS_SHED_MS", "5")
        reg = Registry()
        s = VerifyScheduler(
            spec="cpu", flush_us=10_000_000, lane_budget=100_000,
            qos="consensus:block:8,blocksync:shed:8,mempool:drop:8",
            qos_metrics=QoSMetrics(reg), tenant_rate=4,
        )
        s.start()
        try:
            s.submit(_make_items(8, tag=b"x0"), subsystem="blocksync")
            s.submit(_make_items(8, tag=b"x1"), subsystem="blocksync")
            s.submit(_make_items(8, tag=b"x2"), subsystem="mempool")
            s.submit(_make_items(8, tag=b"x3"), subsystem="mempool")
            s.submit(_make_items(2, tag=b"x4"), subsystem="consensus")
        finally:
            s.stop()
        series = self._parse(reg.expose())
        assert all(
            k.startswith("cometbft_verify_qos_") for k in series
        ), sorted(series)
        get = series.__getitem__
        assert get('cometbft_verify_qos_admits{qclass="blocksync"}') == 1
        assert get('cometbft_verify_qos_admits{qclass="consensus"}') == 1
        # the second blocksync/mempool submit exceeds the tenant's 8-sig
        # burst (rate 4 x factor 2): shed resp. drop, both counted
        assert get(
            'cometbft_verify_qos_sheds{policy="shed",qclass="blocksync"}'
        ) == 1
        assert get(
            'cometbft_verify_qos_sheds{policy="drop",qclass="mempool"}'
        ) == 1
        assert get(
            'cometbft_verify_qos_shed_sigs{qclass="mempool"}'
        ) == 8
        assert get(
            'cometbft_verify_qos_quota_rejections{tenant="blocksync"}'
        ) == 1
        assert get(
            'cometbft_verify_qos_quota_rejections{tenant="mempool"}'
        ) == 1


class TestStopRace:
    def test_submitters_racing_stop_leak_no_futures(self):
        # N threads pound a tiny lane (bound 8) with block policy while
        # the main thread stops the scheduler: every future must
        # complete — admitted ones via the final drain, late ones via
        # the post-stop inline path, blocked ones released by stop's
        # notify (the _accepting flip)
        s = VerifyScheduler(
            spec="cpu", flush_us=10_000_000, lane_budget=100_000,
            qos="consensus:block:8", submit_timeout_ms=5000,
        )
        s.start()
        futs = []
        mtx = threading.Lock()
        start = threading.Barrier(9)

        def submitter(i):
            start.wait()
            for j in range(5):
                f = s.submit(
                    _make_items(8, tag=bytes([i, j])),
                    subsystem="consensus",
                )
                with mtx:
                    futs.append(f)

        threads = [
            threading.Thread(target=submitter, args=(i,)) for i in range(8)
        ]
        for th in threads:
            th.start()
        start.wait()
        time.sleep(0.01)  # let the lane fill and submitters block
        t0 = time.monotonic()
        s.stop()
        for th in threads:
            th.join(timeout=30)
        assert all(not th.is_alive() for th in threads)
        # stop released the 5s backpressure waiters immediately
        assert time.monotonic() - t0 < 4.0
        assert len(futs) == 40
        for f in futs:
            ok, mask = f.result(timeout=10)  # no leaked/hung future
            assert ok and len(mask) == 8

    def test_post_stop_submit_is_inline(self):
        s = VerifyScheduler(spec="cpu", qos="default")
        s.start()
        s.stop()
        f = s.submit(_make_items(4, tag=b"post"), subsystem="mempool")
        ok, mask = f.result(timeout=0)
        assert ok and all(mask)


class _BrokenSupervisor:
    """Duck-typed supervisor stub: breaker open, CPU-exact verdicts."""

    def state(self):
        return "broken"

    def verify_items(self, items, reason=None, origins=None):
        from cometbft_tpu.crypto.batch import CPUBatchVerifier

        bv = CPUBatchVerifier()
        for pk, m, s in items:
            bv.add(pk, m, s)
        return bv.verify()[1]


class TestBrokenFlushReason:
    def test_broken_breaker_flushes_immediately_and_is_counted(self):
        s = VerifyScheduler(
            spec="cpu", flush_us=5_000_000, qos="default",
            supervisor=_BrokenSupervisor(),
        )
        s.start()
        try:
            f = s.submit(_make_items(4, tag=b"br"), subsystem="consensus")
            ok, mask = f.result(timeout=10)  # no 5s flush_us wait
            assert ok and all(mask)
            snap = s.queue_snapshot()
            assert snap["flush_reasons"]["broken"] >= 1
        finally:
            s.stop()

    def test_verify_top_renders_broken_count_and_qos_section(self):
        from tools.verify_top import render

        s = VerifyScheduler(spec="cpu", qos="default")
        snap = {
            "slo": {"target_ms": 25, "burn_rate": 0.0},
            "headroom": {},
            "window_s": 60,
            "sources": {"scheduler": s.queue_snapshot()},
            "subsystems": {},
            "devices": {},
        }
        frame = render(snap)
        assert "broken_flushes=0" in frame
        assert "qos classes:" in frame
        assert "consensus" in frame and "mempool" in frame
        assert "brownout  disabled=-" in frame
        # QoS off: no qos section, the routing line still shows broken
        s2 = VerifyScheduler(spec="cpu", qos="off")
        snap["sources"]["scheduler"] = s2.queue_snapshot()
        frame2 = render(snap)
        assert "broken_flushes=0" in frame2
        assert "qos classes:" not in frame2


class TestFifoCompat:
    def test_off_is_single_fifo(self):
        s = VerifyScheduler(spec="cpu", qos="off")
        assert not s.qos_enabled
        assert s.queue_snapshot()["qos"] == {"enabled": False}
        assert s.brownout is None

    def test_env_off_beats_constructor(self, monkeypatch):
        monkeypatch.setenv("CBFT_QOS_CLASSES", "off")
        s = VerifyScheduler(spec="cpu", qos="default")
        assert not s.qos_enabled


class TestChaosOverloadRung:
    def test_overload_rung_end_to_end(self):
        from cometbft_tpu.crypto.faults import run_chaos_overload

        s = run_chaos_overload(seed=23, flood_s=1.0)
        assert s["wrong_verdicts"] == 0
        assert s["latency_ok"], (
            f"loaded p99 {s['loaded_p99_ms']}ms over bound "
            f"{s['latency_bound_ms']}ms"
        )
        assert s["consensus_sheds"] == 0
        assert s["consensus_drops"] == 0
        assert s["consensus_backpressure_timeouts"] == 0
        assert s["flood_sheds"] >= 1
        assert s["flood_drops"] >= 1
        assert s["rejected"] >= 1
        assert s["brownout"]["trips"] >= 1
        assert s["brownout"]["readmissions"] >= 1
        assert not s["brownout"]["disabled"]
        assert s["readmitted"]
        assert s["starved_without_qos"], (
            f"qos-off p99 {s['qos_off_p99_ms']}ms did not exceed the "
            f"bound {s['latency_bound_ms']}ms the qos-on phase met"
        )
