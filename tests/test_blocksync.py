"""Blocksync: message codec, BlockPool scheduling, and a full fast-sync of a
multi-hundred-block chain over real TCP.

Model: reference blockchain/v0/pool_test.go + reactor_test.go
(TestNoBlockResponse, TestFastSyncBasic-style: a fresh node syncs from a
peer with a prebuilt chain, then switches to consensus).
"""

import threading
import time

import pytest

from cometbft_tpu.abci.client import LocalClient
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.blocksync import (
    BLOCKSYNC_CHANNEL,
    BlockPool,
    BlockRequest,
    BlockResponse,
    BlocksyncReactor,
    NoBlockResponse,
    StatusRequest,
    StatusResponse,
    decode_blocksync_message,
    encode_blocksync_message,
)
from cometbft_tpu.config import test_config as make_test_config
from cometbft_tpu.consensus.reactor import ConsensusReactor
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import NilWAL
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.p2p import (
    MultiplexTransport,
    NetAddress,
    NodeInfo,
    NodeKey,
    ProtocolVersion,
    Switch,
)
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.proxy import AppConnConsensus
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types import test_util
from cometbft_tpu.types.block import Block, Commit
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

CHAIN_ID = "blocksync-test-chain"
GENESIS_TIME = Timestamp(1_700_000_000, 0)


class TestBlocksyncCodec:
    def test_all_messages_roundtrip(self):
        blk = Block()
        blk.header.height = 7
        blk.header.chain_id = CHAIN_ID
        blk.last_commit = Commit(height=6, round=0)
        msgs = [
            BlockRequest(12),
            NoBlockResponse(13),
            BlockResponse(blk),
            StatusRequest(),
            StatusResponse(100, 1),
        ]
        for m in msgs:
            dec = decode_blocksync_message(encode_blocksync_message(m))
            assert type(dec) is type(m)
        dec = decode_blocksync_message(
            encode_blocksync_message(BlockResponse(blk))
        )
        assert dec.block.header.height == 7
        dec = decode_blocksync_message(
            encode_blocksync_message(StatusResponse(100, 1))
        )
        assert (dec.height, dec.base) == (100, 1)

    def test_malformed_raises(self):
        with pytest.raises(Exception):
            decode_blocksync_message(b"")


class TestBlockPool:
    def _mk(self, start=1):
        requests = []
        errors = []
        pool = BlockPool(
            start,
            lambda h, p: requests.append((h, p)),
            lambda e, p: errors.append((e, p)),
        )
        return pool, requests, errors

    def test_dispatches_requests_to_peers(self):
        pool, requests, _ = self._mk()
        pool.start()
        try:
            pool.set_peer_range("peerA", 1, 10)
            deadline = time.monotonic() + 5
            while len(requests) < 10 and time.monotonic() < deadline:
                time.sleep(0.02)
            heights = sorted(h for h, _ in requests)
            assert heights == list(range(1, 11))
        finally:
            pool.stop()

    def test_backpressure_per_peer(self):
        pool, requests, _ = self._mk()
        pool.start()
        try:
            pool.set_peer_range("peerA", 1, 500)
            time.sleep(0.3)
            # only maxPendingRequestsPerPeer in flight on one peer
            assert len(requests) == 20
        finally:
            pool.stop()

    def test_add_block_and_window(self):
        pool, requests, errors = self._mk()
        pool.start()
        try:
            pool.set_peer_range("peerA", 1, 10)
            deadline = time.monotonic() + 5
            while len(requests) < 5 and time.monotonic() < deadline:
                time.sleep(0.02)
            blocks = {}
            for h in range(1, 6):
                b = Block()
                b.header.height = h
                blocks[h] = b
            # out-of-order arrival
            for h in (3, 1, 2, 5, 4):
                pool.add_block("peerA", blocks[h], 100)
            window = pool.peek_window(10)
            assert [b.header.height for b in window] == [1, 2, 3, 4, 5]
            first, second = pool.peek_two_blocks()
            assert first.header.height == 1 and second.header.height == 2
            pool.pop_request()
            assert pool.peek_two_blocks()[0].header.height == 2
            assert not errors
        finally:
            pool.stop()

    def test_block_from_wrong_peer_rejected(self):
        pool, requests, errors = self._mk()
        pool.start()
        try:
            pool.set_peer_range("peerA", 1, 5)
            deadline = time.monotonic() + 5
            while not requests and time.monotonic() < deadline:
                time.sleep(0.02)
            b = Block()
            b.header.height = requests[0][0]
            pool.add_block("peerB", b, 100)  # not the assigned peer
            assert errors and errors[0][1] == "peerB"
            assert pool.peek_two_blocks() == (None, None)
        finally:
            pool.stop()

    def test_redo_request_drops_peer_blocks(self):
        pool, requests, _ = self._mk()
        pool.start()
        try:
            pool.set_peer_range("peerA", 1, 5)
            deadline = time.monotonic() + 5
            while len(requests) < 3 and time.monotonic() < deadline:
                time.sleep(0.02)
            for h in (1, 2, 3):
                b = Block()
                b.header.height = h
                pool.add_block("peerA", b, 100)
            assert pool.redo_request(1) == "peerA"
            # every block from the bad peer is gone
            assert pool.peek_two_blocks() == (None, None)
            assert pool.num_peers() == 0
        finally:
            pool.stop()

    def test_is_caught_up(self):
        pool, _, _ = self._mk(start=11)
        pool.start()
        try:
            assert not pool.is_caught_up()  # no peers
            pool.set_peer_range("peerA", 1, 10)
            # our 11 >= 10-1: caught up once received-or-waited is true
            b = Block()
            b.header.height = 11
            # received_any is set through add_block only for wanted heights;
            # instead rely on the 5s grace — simulate by backdating
            pool._start_time -= 10
            assert pool.is_caught_up()
            pool.set_peer_range("peerB", 1, 100)
            assert not pool.is_caught_up()
        finally:
            pool.stop()


# -- full TCP fast-sync ------------------------------------------------------


def _build_chain_node(doc, privs, n_blocks):
    """A node whose stores hold n_blocks committed blocks (built through the
    real executor so app hashes line up)."""
    state = make_genesis_state(doc)
    state_store = Store(MemDB())
    state_store.save(state)
    block_store = BlockStore(MemDB())
    client = LocalClient(KVStoreApplication())
    client.start()
    executor = BlockExecutor(state_store, AppConnConsensus(client))

    from cometbft_tpu.types.block import BlockID

    last_commit = Commit(height=0, round=0)
    for h in range(1, n_blocks + 1):
        proposer = state.validators.validators[h % len(privs)].address
        block, parts = executor.create_proposal_block(
            h, state, last_commit, proposer
        )
        block_id = BlockID(block.hash(), parts.header())
        seen_commit = test_util.make_commit(
            block_id, h, 0, state.validators, privs, doc.chain_id,
            now=Timestamp(GENESIS_TIME.seconds + h, 0),
        )
        block_store.save_block(block, parts, seen_commit)
        state, _ = executor.apply_block(state, block_id, block)
        last_commit = seen_commit
    return state, state_store, block_store, client


class _SyncNode:
    """A node (server or fresh syncer) with blocksync + consensus reactors."""

    def __init__(self, doc, priv_val, state, state_store, block_store, client,
                 fast_sync, verify_window=16):
        self.state_store = state_store
        self.block_store = block_store
        self.client = client
        executor = BlockExecutor(state_store, AppConnConsensus(client))
        cfg = make_test_config().consensus
        cfg.wal_path = ""
        self.cons = ConsensusState(
            cfg, state, executor, block_store, wal=NilWAL()
        )
        if priv_val is not None:
            self.cons.set_priv_validator(priv_val)
        self.cons_reactor = ConsensusReactor(
            self.cons, wait_sync=fast_sync
        )
        self.bs_reactor = BlocksyncReactor(
            state, executor, block_store, fast_sync=fast_sync,
            verify_window=verify_window,
        )
        self.node_key = NodeKey(ed.gen_priv_key())
        info = NodeInfo(
            protocol_version=ProtocolVersion(),
            node_id=self.node_key.id(),
            listen_addr="127.0.0.1:0",
            network=doc.chain_id,
            channels=bytes([BLOCKSYNC_CHANNEL, 0x20, 0x21, 0x22, 0x23]),
            moniker="bs-test",
        )
        self.transport = MultiplexTransport(info, self.node_key)
        self.transport.listen(NetAddress("", "127.0.0.1", 0))
        info.listen_addr = f"127.0.0.1:{self.transport.listen_addr.port}"
        self.switch = Switch(self.transport, reconnect_interval=0.2)
        self.switch.add_reactor("BLOCKSYNC", self.bs_reactor)
        self.switch.add_reactor("CONSENSUS", self.cons_reactor)

    def start(self):
        self.switch.start()

    def stop(self):
        for svc in (self.switch, self.client):
            try:
                if svc.is_running():
                    svc.stop()
            except Exception:
                pass


def _make_doc(n_vals=4):
    vals, privs = test_util.deterministic_validator_set(n_vals, 10)
    doc = GenesisDoc(
        genesis_time=GENESIS_TIME,
        chain_id=CHAIN_ID,
        validators=[
            GenesisValidator(v.address, v.pub_key, v.voting_power, "")
            for v in vals.validators
        ],
    )
    return doc, vals, privs


@pytest.mark.slow
class TestFastSyncOverTCP:
    def test_fresh_node_syncs_500_blocks_and_switches(self):
        doc, vals, privs = _make_doc()
        n_blocks = 500
        state, ss, bs, client = _build_chain_node(doc, privs, n_blocks)
        server = _SyncNode(doc, None, state, ss, bs, client, fast_sync=False)

        fresh_state = make_genesis_state(doc)
        fss = Store(MemDB())
        fss.save(fresh_state)
        fclient = LocalClient(KVStoreApplication())
        fclient.start()
        fresh = _SyncNode(
            doc, privs[0], fresh_state, fss, BlockStore(MemDB()), fclient,
            fast_sync=True,
        )
        server.start()
        fresh.start()
        try:
            fresh.switch.dial_peer_with_address(server.transport.listen_addr)
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if fresh.block_store.height() >= n_blocks - 1:
                    break
                time.sleep(0.25)
            assert fresh.block_store.height() >= n_blocks - 1, (
                f"synced only to {fresh.block_store.height()}"
            )
            # blocks match the server's bit for bit
            for h in (1, 100, n_blocks // 2, n_blocks - 1):
                want = server.block_store.load_block_meta(h).block_id.hash
                got = fresh.block_store.load_block_meta(h).block_id.hash
                assert want == got, f"height {h} diverged"
            # and consensus took over
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if not fresh.cons_reactor.wait_sync():
                    break
                time.sleep(0.1)
            assert not fresh.cons_reactor.wait_sync(), "switch_to_consensus never fired"
        finally:
            fresh.stop()
            server.stop()
