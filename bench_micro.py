"""Micro-benchmark harnesses mirroring the reference's in-repo Go
benchmarks (values are machine-dependent; none are stored — the harness
IS the parity surface):

  ed25519        — crypto/ed25519/bench_test.go:11-26 Sign/Verify, plus
                   the 64-sig batch through the BatchVerifier boundary
  validator_set  — types/validator_set_test.go:167,1685 copy/update
  light          — light/client_benchmark_test.go:29-84 sequential vs
                   bisection verification
  mempool        — mempool/v0/bench_test.go:13-82 CheckTx + Reap
  wal            — consensus/wal_test.go write throughput
  scheduler      — VerifyScheduler coalescing contract (no Go analogue:
                   fewer dispatches than concurrent submitters, serial-
                   identical verdicts, deadline-bounded sub-floor flush)

Run: python bench_micro.py [section ...]   (default: all, one JSON line
per section). The headline TPU-vs-CPU bench stays in bench.py.
"""

from __future__ import annotations

import json
import sys
import time


def _rate(n: int, fn) -> float:
    t0 = time.perf_counter()
    fn()
    return round(n / (time.perf_counter() - t0), 1)


def bench_ed25519() -> dict:
    from bench import bench_cpu_batch  # the shared 64-sig boundary bench
    from cometbft_tpu.crypto import ed25519 as ed

    n = 400
    key = ed.gen_priv_key()
    msg = b"x" * 128
    sign_rate = _rate(n, lambda: [key.sign(msg) for _ in range(n)])
    sig = key.sign(msg)
    pub = key.pub_key()
    verify_rate = _rate(
        n, lambda: [pub.verify_signature(msg, sig) for _ in range(n)]
    )
    return {
        "sign_per_sec": sign_rate,
        "verify_per_sec": verify_rate,
        "batch64_verify_per_sec": round(bench_cpu_batch(n=n), 1),
    }


def bench_validator_set() -> dict:
    from cometbft_tpu.types.test_util import deterministic_validator_set

    vals, _ = deterministic_validator_set(100, 10)
    n = 200
    copy_rate = _rate(n, lambda: [vals.copy() for _ in range(n)])

    def updates():
        for i in range(n):
            v = vals.copy()
            v.increment_proposer_priority(1)

    return {
        "copy_100vals_per_sec": copy_rate,
        "increment_priority_per_sec": _rate(n, updates),
        "hash_100vals_ms": round(
            _ms(lambda: vals.hash()), 3
        ),
    }


def _ms(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return (time.perf_counter() - t0) * 1e3


def bench_light() -> dict:
    """Sequential vs bisection verification over a 64-block chain
    (light/client_benchmark_test.go:29-84 shape, in-memory provider).
    Reuses the test suite's chain fixture — the bench is the harness,
    not a second implementation of header signing."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "light_fixtures",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tests", "test_light.py"),
    )
    fx = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fx)

    from cometbft_tpu.libs.db import MemDB
    from cometbft_tpu.light import Client, TrustOptions
    from cometbft_tpu.light.provider import MockProvider
    from cometbft_tpu.light.store import DBStore

    blocks, _, _ = fx._light_chain(64, n_vals=10)
    out = {}
    for mode in ("sequential", "bisection"):
        opts = TrustOptions(
            period_ns=fx.WEEK_NS,
            height=1,
            hash=blocks[1].signed_header.header.hash(),
        )
        client = Client(
            fx.CHAIN_ID,
            opts,
            MockProvider(fx.CHAIN_ID, blocks),
            [],
            DBStore(MemDB()),
            sequential=(mode == "sequential"),
        )
        t0 = time.perf_counter()
        lb = client.verify_light_block_at_height(64, fx._ts(65))
        assert lb.height == 64
        out[f"{mode}_to_h64_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
    return out


def bench_mempool() -> dict:
    from cometbft_tpu.abci.client import LocalClient
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.config import MempoolConfig
    from cometbft_tpu.mempool.clist_mempool import CListMempool

    client = LocalClient(KVStoreApplication())
    client.start()
    try:
        mp = CListMempool(MempoolConfig(), client, height=0)
        n = 2000

        def checks():
            for i in range(n):
                mp.check_tx(b"k%d=v" % i)
            mp.flush_app_conn()

        check_rate = _rate(n, checks)
        reap_ms = _ms(lambda: mp.reap_max_bytes_max_gas(-1, -1))
        return {
            "check_tx_per_sec": check_rate,
            "reap_2000_ms": round(reap_ms, 2),
        }
    finally:
        client.stop()


def bench_wal() -> dict:
    import tempfile

    from cometbft_tpu.consensus.wal import WAL, EndHeightMessage

    n = 500
    with tempfile.TemporaryDirectory() as d:
        wal = WAL(d + "/wal")
        wal.start()
        t0 = time.perf_counter()
        for i in range(n):
            wal.write(EndHeightMessage(i + 1))
        wal.flush_and_sync()
        rate = n / (time.perf_counter() - t0)
        t0 = time.perf_counter()
        for i in range(100):
            wal.write_sync(EndHeightMessage(n + i + 1))
        sync_rate = 100 / (time.perf_counter() - t0)
        wal.stop()
    return {
        "writes_per_sec": round(rate, 1),
        "write_syncs_per_sec": round(sync_rate, 1),
    }


def bench_routing() -> dict:
    """Measurement-driven routing regressions, asserted on CPU-only CI:

    - verify_commit with a tpu BackendSpec whose floor admits the commit
      must route through the RESIDENT fixed-executable path (the p50
      path — crypto/tpu/ed25519_batch.py verify_valset_resident);
    - 10k merkle leaves must stay on the host tree when no calibration
      table proved the device wins (round 5: device loses 4.5× there);
    - a synthetic crossover table must flip both verdicts, proving
      routing reads the table rather than a constant.

    Keys are positive counts/values so the harness's ">0" invariant
    doubles as the assertion surface.
    """
    import os
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["CBFT_TPU_PROBE"] = "0"  # trust the (virtual) platform
    os.environ.pop("CBFT_TPU_MIN_BATCH", None)
    os.environ.pop("CBFT_TPU_MERKLE_MIN_LEAVES", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    cache = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
    )
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

    from cometbft_tpu.crypto import batch as cryptobatch
    from cometbft_tpu.crypto.batch import BackendSpec
    from cometbft_tpu.crypto.tpu import calibrate, ed25519_batch
    from cometbft_tpu.crypto.tpu import merkle as tpu_merkle
    from cometbft_tpu.types import test_util

    out = {}
    try:
        with tempfile.TemporaryDirectory() as d:
            # no table: no device claim proven → merkle stays host and
            # the ed floor falls back to the conservative constant
            calibrate.set_table_path(os.path.join(d, "absent.json"))
            if tpu_merkle.device_wins(10_000):
                raise AssertionError("10k leaves routed to device w/o table")
            out["merkle_10k_on_host"] = 1
            out["ed25519_floor_default"] = cryptobatch.ed25519_routing_floor()

            # synthetic table: both crossover verdicts must be read back
            path = os.path.join(d, "cal.json")
            calibrate.save_table(
                {
                    "version": calibrate.TABLE_VERSION,
                    "merkle_min_leaves": 512,
                    "ed25519_min_batch": 256,
                },
                path,
            )
            calibrate.set_table_path(path)
            if not tpu_merkle.device_wins(10_000):
                raise AssertionError("calibrated merkle crossover ignored")
            if cryptobatch.ed25519_routing_floor() != 256:
                raise AssertionError("calibrated ed25519 floor ignored")
            out["merkle_crossover_respected"] = 1
            out["ed25519_floor_calibrated"] = (
                cryptobatch.ed25519_routing_floor()
            )
    finally:
        calibrate.set_table_path(None)

    # resident p50 routing: small valset, floor lowered via BackendSpec
    # (not env) — the exact plumbing node._setup threads per node
    chain_id = "bench-routing"
    vals, privs = test_util.deterministic_validator_set(4, 10)
    bid = test_util.make_block_id()
    commit = test_util.make_commit(bid, 5, 0, vals, privs, chain_id)
    calls = {"n": 0}
    real = ed25519_batch.verify_valset_resident

    def spy(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    ed25519_batch.verify_valset_resident = spy
    try:
        t0 = time.perf_counter()
        vals.verify_commit(
            chain_id, bid, 5, commit, backend=BackendSpec("tpu", min_batch=1)
        )
        ms = (time.perf_counter() - t0) * 1e3
    finally:
        ed25519_batch.verify_valset_resident = real
    if calls["n"] != 1:
        raise AssertionError(
            f"verify_commit made {calls['n']} resident calls, wanted 1"
        )
    out["resident_route_hits"] = calls["n"]
    out["verify_commit_resident_ms"] = round(ms, 2)
    return out


def bench_scheduler() -> dict:
    """The VerifyScheduler coalescing contract, asserted on CPU-only CI:

    - four threads each submitting a 64-sig request concurrently must
      produce STRICTLY FEWER backend dispatches than four, with
      per-request verdicts identical to running each request serially
      through CPUBatchVerifier (including a poisoned request whose bad
      signature must not leak into its neighbours' verdicts);
    - a lone sub-floor request must complete within 10× flush_us — the
      deadline flush, not the lane budget, is what releases it.

    Keys are positive counts/values so the harness's ">0" invariant
    doubles as the assertion surface.
    """
    import os
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["CBFT_TPU_PROBE"] = "0"

    from bench import _make_batch
    from cometbft_tpu.crypto import batch as cryptobatch
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto.batch import BackendSpec, CPUBatchVerifier
    from cometbft_tpu.crypto.scheduler import VerifyScheduler

    dispatches = {"n": 0}

    class CountingVerifier(CPUBatchVerifier):
        def verify(self):
            dispatches["n"] += 1
            return super().verify()

    cryptobatch.register_backend("counting", CountingVerifier)

    n_callers, per_caller = 4, 64
    reqs = [
        [
            (ed.PubKeyEd25519(pk), m, s)
            for pk, m, s in zip(*_make_batch(per_caller))
        ]
        for _ in range(n_callers)
    ]
    # poison request 2: its verdicts must come back per-slice, leaving
    # the other callers' all-ok untouched
    pk, m, _ = reqs[2][5]
    reqs[2][5] = (pk, m, b"\x00" * 64)

    def serial_verdict(items):
        bv = CPUBatchVerifier()
        for k, msg, sig in items:
            bv.add(k, msg, sig)
        return bv.verify()

    serial = [serial_verdict(items) for items in reqs]

    sched = VerifyScheduler(spec=BackendSpec("counting"), flush_us=5000)
    sched.start()
    try:
        results = [None] * n_callers
        barrier = threading.Barrier(n_callers)

        def worker(i):
            barrier.wait()
            results[i] = sched.submit(reqs[i]).result(timeout=60)

        ts = [
            threading.Thread(target=worker, args=(i,))
            for i in range(n_callers)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if dispatches["n"] >= n_callers:
            raise AssertionError(
                f"{n_callers} concurrent submitters cost {dispatches['n']} "
                f"dispatches — no coalescing"
            )
        if results != serial:
            raise AssertionError("coalesced verdicts diverge from serial")
        if results[2][0] or not all(results[i][0] for i in (0, 1, 3)):
            raise AssertionError("poisoned request leaked into neighbours")
        out = {
            "coalesced_dispatches": dispatches["n"],
            "dispatch_savings": n_callers - dispatches["n"],
            "verdicts_match_serial": 1,
            "poison_isolated": 1,
        }

        # lone sub-floor request: only the deadline can release it
        t0 = time.perf_counter()
        ok, mask = sched.submit(reqs[0][:3]).result(timeout=60)
        dt = time.perf_counter() - t0
        if not (ok and len(mask) == 3):
            raise AssertionError("sub-floor request verdict wrong")
        bound_s = 10 * sched.flush_us / 1e6
        if dt > bound_s:
            raise AssertionError(
                f"sub-floor request took {dt * 1e3:.1f}ms > 10×flush_us "
                f"({bound_s * 1e3:.0f}ms)"
            )
        out["sub_floor_latency_ms"] = round(dt * 1e3, 2)
        out["deadline_bound_ms"] = round(bound_s * 1e3, 1)
    finally:
        sched.stop()
    return out


def bench_telemetry() -> dict:
    """Capacity-telemetry overhead (crypto/telemetry.py), asserted on
    CPU-only CI with the real ed25519 verify cost dominating:

    - an identical scheduler workload (8 requests × 64 real ed25519
      sigs through BackendSpec("cpu")) is timed with the TelemetryHub
      wired in and with telemetry=None, best-of-3 per mode, modes
      interleaved so machine noise hits both equally;
    - hub-on throughput must be within 1% of hub-off throughput — the
      telemetry layer's "hot path is appends and counter bumps"
      contract, measured rather than asserted from the docstring;
    - the hub must actually have metered the work: the snapshot's RED
      table shows every request under the "bench" subsystem.

    ``overhead_margin_pct`` is ``1.0 − overhead_pct`` so the harness's
    ">0" invariant IS the <1% assertion (and survives the common case
    where measured overhead is ≤0 inside noise).
    """
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["CBFT_TPU_PROBE"] = "0"

    from bench import _make_batch
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto import telemetry as telemetrylib
    from cometbft_tpu.crypto.batch import BackendSpec
    from cometbft_tpu.crypto.scheduler import VerifyScheduler

    n_reqs, per_req = 8, 64
    pks, msgs, sigs = _make_batch(per_req)
    items = [
        (ed.PubKeyEd25519(pk), m, s) for pk, m, s in zip(pks, msgs, sigs)
    ]
    reqs = [list(items) for _ in range(n_reqs)]

    def run_workload(hub) -> float:
        sched = VerifyScheduler(
            spec=BackendSpec("cpu"), flush_us=500, telemetry=hub
        )
        sched.start()
        try:
            # warm once outside the timed region (thread spin-up,
            # first-flush costs are identical per mode but noisy)
            sched.submit(reqs[0], subsystem="bench").result(timeout=60)
            t0 = time.perf_counter()
            futs = [
                sched.submit(r, subsystem="bench", height=i + 1)
                for i, r in enumerate(reqs)
            ]
            for f in futs:
                ok, mask = f.result(timeout=60)
                if not (ok and all(mask)):
                    raise AssertionError("telemetry bench verdict wrong")
            return time.perf_counter() - t0
        finally:
            sched.stop()

    hub = telemetrylib.TelemetryHub(
        metrics=telemetrylib.Metrics.nop(), slo_target_ms=100
    )
    off_s, on_s = [], []
    for _ in range(3):  # interleave so drift hits both modes equally
        off_s.append(run_workload(None))
        on_s.append(run_workload(hub))
    base, teled = min(off_s), min(on_s)

    snap = hub.snapshot()
    red = snap["subsystems"].get("bench", {})
    if red.get("requests", 0) < 3 * (n_reqs + 1):
        raise AssertionError(
            f"hub metered {red.get('requests', 0)} bench requests, "
            f"expected {3 * (n_reqs + 1)} — telemetry was not engaged"
        )

    overhead_pct = (teled - base) / base * 100.0
    if overhead_pct >= 1.0:
        raise AssertionError(
            f"telemetry overhead {overhead_pct:.2f}% >= 1% budget "
            f"(off={base * 1e3:.1f}ms on={teled * 1e3:.1f}ms)"
        )
    total_sigs = n_reqs * per_req
    return {
        "baseline_ms": round(base * 1e3, 2),
        "telemetry_ms": round(teled * 1e3, 2),
        "baseline_sigs_per_sec": round(total_sigs / base, 1),
        "telemetry_sigs_per_sec": round(total_sigs / teled, 1),
        "overhead_margin_pct": round(1.0 - overhead_pct, 3),
        "metered_requests": red.get("requests", 0),
    }


def bench_memory() -> dict:
    """Memory-plane overhead (crypto/tpu/memory.py), asserted on
    CPU-only CI with the real ed25519 verify cost dominating:

    - the bench_telemetry workload (8 requests × 64 real ed25519 sigs
      through BackendSpec("cpu")) is timed with a model-only
      MemoryPlane installed as the process default (poll_ms=0, so the
      scheduler's ride-along poll fires on EVERY dispatch — worst case)
      and with no plane installed, best-of-3 per mode, interleaved;
    - plane-on throughput must be within 1% of plane-off throughput —
      the "hot path is a clock compare" contract, measured;
    - the plane must actually have polled: its polls counter grew by at
      least one per plane-on arm (the scheduler coalesces submissions,
      so the dispatch count — not the request count — is the floor).

    ``overhead_margin_pct`` is ``1.0 − overhead_pct`` so the harness's
    ">0" invariant IS the <1% assertion.
    """
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["CBFT_TPU_PROBE"] = "0"

    from bench import _make_batch
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto.batch import BackendSpec
    from cometbft_tpu.crypto.scheduler import VerifyScheduler
    from cometbft_tpu.crypto.tpu import memory as memlib

    n_reqs, per_req = 8, 64
    pks, msgs, sigs = _make_batch(per_req)
    items = [
        (ed.PubKeyEd25519(pk), m, s) for pk, m, s in zip(pks, msgs, sigs)
    ]
    reqs = [list(items) for _ in range(n_reqs)]

    def run_workload() -> float:
        sched = VerifyScheduler(spec=BackendSpec("cpu"), flush_us=500)
        sched.start()
        try:
            sched.submit(reqs[0], subsystem="bench").result(timeout=60)
            t0 = time.perf_counter()
            futs = [sched.submit(r, subsystem="bench") for r in reqs]
            for f in futs:
                ok, mask = f.result(timeout=60)
                if not (ok and all(mask)):
                    raise AssertionError("memory bench verdict wrong")
            return time.perf_counter() - t0
        finally:
            sched.stop()

    plane = memlib.MemoryPlane(poll_ms=0, stats=False)
    off_s, on_s = [], []
    prev = memlib.set_default_plane(None)
    try:
        for _ in range(3):  # interleave so drift hits both modes equally
            memlib.set_default_plane(None)
            off_s.append(run_workload())
            memlib.set_default_plane(plane)
            on_s.append(run_workload())
    finally:
        memlib.set_default_plane(prev)
    base, planed = min(off_s), min(on_s)

    polls = plane.metrics.polls.value()
    if polls < 3:
        raise AssertionError(
            f"plane polled {polls} times, expected >= 3 "
            "— the scheduler ride-along poll was not engaged"
        )

    overhead_pct = (planed - base) / base * 100.0
    if overhead_pct >= 1.0:
        raise AssertionError(
            f"memory-plane overhead {overhead_pct:.2f}% >= 1% budget "
            f"(off={base * 1e3:.1f}ms on={planed * 1e3:.1f}ms)"
        )
    total_sigs = n_reqs * per_req
    return {
        "baseline_ms": round(base * 1e3, 2),
        "memplane_ms": round(planed * 1e3, 2),
        "baseline_sigs_per_sec": round(total_sigs / base, 1),
        "memplane_sigs_per_sec": round(total_sigs / planed, 1),
        "overhead_margin_pct": round(1.0 - overhead_pct, 3),
        "plane_polls": int(polls),
    }


def bench_coldboot() -> dict:
    """AOT warm-boot smoke (crypto/tpu/aot.py), asserted on CPU-only CI
    with the virtual device mesh and the smallest bucket only:

    - run_warm_boot over bucket 64 must leave ≥1 executable resident in
      the process registry;
    - a real 64-sig dispatch AFTER the warm boot must be a registry HIT:
      zero new XLA compilations and zero registry misses (the ROADMAP
      item 2 acceptance contract, smoke-sized) — with verdicts correct.

    The full cold-vs-warm cache timing lives in bench.py's coldboot
    stage; this section fails fast when a registry key drifts away from
    what dispatch_batch actually asks for.
    """
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["CBFT_TPU_PROBE"] = "0"
    import jax

    cache = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
    )
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto.tpu import aot, ed25519_batch
    from cometbft_tpu.crypto.tpu import mesh as mesh_mod

    reg = aot.default_registry()
    # single-device variants are skipped: with the virtual mesh up,
    # dispatch_batch always takes the sharded path, and the smoke must
    # fit the tier-1 budget (every compile here is a CPU XLA compile)
    include_single = mesh_mod.n_devices() == 1
    t0 = time.perf_counter()
    obs = aot.run_warm_boot(sizes=[64], include_single=include_single)
    warm_ms = (time.perf_counter() - t0) * 1e3
    if not obs:
        raise AssertionError("warm boot planned no targets")

    misses_before = reg.metrics.registry_misses.value()
    compiles_before = reg.compile_count
    key = ed.gen_priv_key_from_secret(b"coldboot-smoke")
    pk, msg = key.pub_key().bytes(), b"warm boot smoke message ......."
    sig = key.sign(msg)
    t0 = time.perf_counter()
    mask = ed25519_batch.verify_batch([pk] * 64, [msg] * 64, [sig] * 64)
    first_ms = (time.perf_counter() - t0) * 1e3
    if not all(mask):
        raise AssertionError("post-warm-boot verdict wrong")
    if reg.compile_count != compiles_before:
        raise AssertionError(
            "dispatch at a warmed bucket paid "
            f"{reg.compile_count - compiles_before} fresh compiles"
        )
    if reg.metrics.registry_misses.value() != misses_before:
        raise AssertionError(
            "dispatch at a warmed bucket missed the executable registry"
        )
    return {
        "warm_targets": len(obs),
        "warm_boot_ms": round(warm_ms, 1),
        "first_verdict_ms": round(first_ms, 1),
        "zero_compile_dispatch": 1,
    }


def bench_wire() -> dict:
    """Wire-ledger overhead (crypto/wire.py), asserted on CPU-only CI
    with the real ed25519 verify cost dominating:

    - the bench_telemetry workload (8 requests × 64 real ed25519 sigs
      through BackendSpec("cpu")) is timed with a WireLedger installed
      as the process default and with no ledger installed, best-of-3
      per mode, interleaved so machine noise hits both equally;
    - ledger-on throughput must be within 1% of ledger-off throughput —
      on the CPU route only the scheduler's demux phase feeds the
      ledger, which is exactly the scheduler-side cost the acceptance
      bound covers (the mesh-side note_chunk rides inside dispatches
      that already cost tens of ms);
    - the ledger must actually have been engaged: every dispatch's
      verdict demux lands one note_demux, so demux_notes must grow by
      at least one per ledger-on arm.

    ``overhead_margin_pct`` is ``1.0 − overhead_pct`` so the harness's
    ">0" invariant IS the <1% assertion.
    """
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["CBFT_TPU_PROBE"] = "0"

    from bench import _make_batch
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto import wire as wirelib
    from cometbft_tpu.crypto.batch import BackendSpec
    from cometbft_tpu.crypto.scheduler import VerifyScheduler

    n_reqs, per_req = 8, 64
    pks, msgs, sigs = _make_batch(per_req)
    items = [
        (ed.PubKeyEd25519(pk), m, s) for pk, m, s in zip(pks, msgs, sigs)
    ]
    reqs = [list(items) for _ in range(n_reqs)]

    def run_workload() -> float:
        sched = VerifyScheduler(spec=BackendSpec("cpu"), flush_us=500)
        sched.start()
        try:
            sched.submit(reqs[0], subsystem="bench").result(timeout=60)
            t0 = time.perf_counter()
            futs = [sched.submit(r, subsystem="bench") for r in reqs]
            for f in futs:
                ok, mask = f.result(timeout=60)
                if not (ok and all(mask)):
                    raise AssertionError("wire bench verdict wrong")
            return time.perf_counter() - t0
        finally:
            sched.stop()

    ledger = wirelib.WireLedger()
    off_s, on_s = [], []
    prev = wirelib.set_default_ledger(None)
    try:
        for _ in range(3):  # interleave so drift hits both modes equally
            wirelib.set_default_ledger(None)
            off_s.append(run_workload())
            wirelib.set_default_ledger(ledger)
            on_s.append(run_workload())
    finally:
        wirelib.set_default_ledger(prev)
    base, led = min(off_s), min(on_s)

    if ledger.demux_notes < 3:
        raise AssertionError(
            f"ledger saw {ledger.demux_notes} demux notes, expected "
            ">= 3 — the scheduler demux feeder was not engaged"
        )

    overhead_pct = (led - base) / base * 100.0
    if overhead_pct >= 1.0:
        raise AssertionError(
            f"wire-ledger overhead {overhead_pct:.2f}% >= 1% budget "
            f"(off={base * 1e3:.1f}ms on={led * 1e3:.1f}ms)"
        )
    total_sigs = n_reqs * per_req
    return {
        "baseline_ms": round(base * 1e3, 2),
        "wire_ms": round(led * 1e3, 2),
        "baseline_sigs_per_sec": round(total_sigs / base, 1),
        "wire_sigs_per_sec": round(total_sigs / led, 1),
        "overhead_margin_pct": round(1.0 - overhead_pct, 3),
        "demux_notes": int(ledger.demux_notes),
    }


def bench_decisions() -> dict:
    """Decision-ledger overhead (crypto/decisions.py), asserted on
    CPU-only CI with the real ed25519 verify cost dominating:

    - the bench_wire workload (8 requests × 64 real ed25519 sigs
      through BackendSpec("cpu")) is timed with a DecisionLedger
      installed as the process default and with no ledger installed,
      best-of-3 per mode, interleaved so machine noise hits both
      equally;
    - ledger-on throughput must be within 1% of ledger-off throughput —
      per flush the decision plane adds one RouteDecision open (inputs
      snapshot + candidate pricing), one thread-local push/pop, and one
      finish (EWMA folds + window deques) against a multi-ms dispatch;
    - the ledger must actually have been engaged: every coalesced flush
      lands exactly one decision record, so the ledger's route counts
      must grow by at least one flush per ledger-on arm.

    ``overhead_margin_pct`` is ``1.0 − overhead_pct`` so the harness's
    ">0" invariant IS the <1% assertion.
    """
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["CBFT_TPU_PROBE"] = "0"

    from bench import _make_batch
    from cometbft_tpu.crypto import decisions as declib
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto.batch import BackendSpec
    from cometbft_tpu.crypto.scheduler import VerifyScheduler

    n_reqs, per_req = 8, 64
    pks, msgs, sigs = _make_batch(per_req)
    items = [
        (ed.PubKeyEd25519(pk), m, s) for pk, m, s in zip(pks, msgs, sigs)
    ]
    reqs = [list(items) for _ in range(n_reqs)]

    def run_workload() -> float:
        sched = VerifyScheduler(spec=BackendSpec("cpu"), flush_us=500)
        sched.start()
        try:
            sched.submit(reqs[0], subsystem="bench").result(timeout=60)
            t0 = time.perf_counter()
            futs = [sched.submit(r, subsystem="bench") for r in reqs]
            for f in futs:
                ok, mask = f.result(timeout=60)
                if not (ok and all(mask)):
                    raise AssertionError("decisions bench verdict wrong")
            return time.perf_counter() - t0
        finally:
            sched.stop()

    ledger = declib.DecisionLedger()
    off_s, on_s = [], []
    prev = declib.set_default_ledger(None)
    try:
        for _ in range(3):  # interleave so drift hits both modes equally
            declib.set_default_ledger(None)
            off_s.append(run_workload())
            declib.set_default_ledger(ledger)
            on_s.append(run_workload())
    finally:
        declib.set_default_ledger(prev)
    base, led = min(off_s), min(on_s)

    n_decisions = sum(ledger.counts().values())
    if n_decisions < 3:
        raise AssertionError(
            f"ledger recorded {n_decisions} decisions, expected >= 3 — "
            "the scheduler's decision feeder was not engaged"
        )

    overhead_pct = (led - base) / base * 100.0
    if overhead_pct >= 1.0:
        raise AssertionError(
            f"decision-ledger overhead {overhead_pct:.2f}% >= 1% budget "
            f"(off={base * 1e3:.1f}ms on={led * 1e3:.1f}ms)"
        )
    total_sigs = n_reqs * per_req
    return {
        "baseline_ms": round(base * 1e3, 2),
        "decisions_ms": round(led * 1e3, 2),
        "baseline_sigs_per_sec": round(total_sigs / base, 1),
        "decisions_sigs_per_sec": round(total_sigs / led, 1),
        "overhead_margin_pct": round(1.0 - overhead_pct, 3),
        "decision_records": int(n_decisions),
    }


def bench_pack() -> dict:
    """Host cost of the compact uint8 pack vs the u32 word pack it
    replaces (crypto/tpu/ed25519_batch.py), asserted on CPU-only CI —
    the ISSUE-13 acceptance bound that moving limb unpacking on-device
    must not sneak extra host time into prepare:

    - both packs run over the same 4096-lane batch, best-of-5 per mode,
      interleaved so machine noise hits both equally; the timed region
      is the full prepare (parse + host SHA-512 + pack) because that is
      the phase the wire ledger attributes as ``pack``;
    - the compact prepare must cost no more than the word prepare plus
      10% measurement headroom — structurally it does strictly less
      work (one transposed byte copy per plane, no u32 word views);
    - both wires must decode to identical verdict inputs (the parity
      property the dedicated tests cover bit-exactly; here a cheap
      reconstruction check guards the bench itself against drift).

    ``pack_margin_pct`` is ``10.0 − overhead_pct`` so the harness's
    ">0" invariant IS the compact-no-slower assertion.
    """
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["CBFT_TPU_PROBE"] = "0"

    import numpy as np

    from bench import _make_batch
    from cometbft_tpu.crypto.tpu import ed25519_batch as eb

    n = 4096
    pks, msgs, sigs = _make_batch(n)

    words_s, compact_s = [], []
    for _ in range(5):  # interleave so drift hits both modes equally
        t0 = time.perf_counter()
        wire_w, valid_w = eb.prepare_batch(pks, msgs, sigs)
        words_s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        wire_c, valid_c = eb.prepare_batch_compact(pks, msgs, sigs)
        compact_s.append(time.perf_counter() - t0)
    base, comp = min(words_s), min(compact_s)

    # parity guard: the compact rows must carry the exact word wire
    r = wire_c.astype(np.uint32)
    rebuilt = (
        r[0::4] | (r[1::4] << 8) | (r[2::4] << 16) | (r[3::4] << 24)
    )
    if not (rebuilt == wire_w).all() or not (valid_w == valid_c).all():
        raise AssertionError("compact wire does not reconstruct the word wire")

    overhead_pct = (comp - base) / base * 100.0
    if overhead_pct >= 10.0:
        raise AssertionError(
            f"compact pack {overhead_pct:.1f}% slower than the word "
            f"pack it replaces (words={base * 1e3:.2f}ms "
            f"compact={comp * 1e3:.2f}ms)"
        )
    return {
        "words_pack_ms": round(base * 1e3, 2),
        "compact_pack_ms": round(comp * 1e3, 2),
        "words_bytes_per_lane": round(wire_w.nbytes / n, 1),
        "compact_bytes_per_lane": round(wire_c.nbytes / n, 1),
        "pack_margin_pct": round(10.0 - overhead_pct, 2),
    }


SECTIONS = {
    "coldboot": bench_coldboot,
    "decisions": bench_decisions,
    "pack": bench_pack,
    "ed25519": bench_ed25519,
    "validator_set": bench_validator_set,
    "light": bench_light,
    "memory": bench_memory,
    "mempool": bench_mempool,
    "routing": bench_routing,
    "scheduler": bench_scheduler,
    "telemetry": bench_telemetry,
    "wal": bench_wal,
    "wire": bench_wire,
}


def main(argv):
    names = argv or sorted(SECTIONS)
    for name in names:
        fn = SECTIONS.get(name)
        if fn is None:
            print(json.dumps({"section": name, "error": "unknown section"}))
            continue
        try:
            print(json.dumps({"section": name, **fn()}))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"section": name, "error": str(exc)[:200]}))


if __name__ == "__main__":
    main(sys.argv[1:])
