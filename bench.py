"""Headline benchmark: batched Ed25519 verification throughput on TPU vs the
reference's serial CPU path.

The reference (dymensionxyz/cometbft) verifies every commit signature one at
a time on one core (types/validator_set.go:685-707 → ed25519.go:148).
Baseline here = that same serial loop on this host's CPU (OpenSSL-backed,
the strongest single-core implementation available). Value = sigs/sec
through the JAX batch kernel on the attached chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np


def _make_batch(n: int):
    from cometbft_tpu.crypto import ed25519 as ed

    rng = np.random.default_rng(42)
    keys = [ed.gen_priv_key_from_secret(bytes([i & 0xFF, i >> 8])) for i in range(min(n, 128))]
    pks, msgs, sigs = [], [], []
    for i in range(n):
        k = keys[i % len(keys)]
        m = rng.bytes(120)  # ~ a canonical vote's sign-bytes size
        pks.append(k.pub_key().bytes())
        msgs.append(m)
        sigs.append(k.sign(m))
    return pks, msgs, sigs


def bench_tpu(pks, msgs, sigs) -> float:
    from cometbft_tpu.crypto.tpu import ed25519_batch

    # warmup: compile + one full pass
    out = ed25519_batch.verify_batch(pks, msgs, sigs)
    assert all(out), "benchmark batch must verify"
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        ed25519_batch.verify_batch(pks, msgs, sigs)
        best = min(best, time.perf_counter() - t0)
    return len(pks) / best


def bench_cpu_serial(pks, msgs, sigs, n: int = 512) -> float:
    from cometbft_tpu.crypto import ed25519 as ed

    keys = [ed.PubKeyEd25519(pk) for pk in pks[:n]]
    t0 = time.perf_counter()
    for k, m, s in zip(keys, msgs[:n], sigs[:n]):
        assert k.verify_signature(m, s)
    dt = time.perf_counter() - t0
    return n / dt


def main():
    batch = 2048
    pks, msgs, sigs = _make_batch(batch)
    cpu = bench_cpu_serial(pks, msgs, sigs)
    tpu = bench_tpu(pks, msgs, sigs)
    print(
        json.dumps(
            {
                "metric": "ed25519_batch_verify_throughput",
                "value": round(tpu, 1),
                "unit": "sigs/sec",
                "vs_baseline": round(tpu / cpu, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
