"""Headline benchmark: batched Ed25519 verification on TPU vs the
reference's CPU paths, plus the north-star VerifyCommit latencies.

The reference (dymensionxyz/cometbft) verifies every commit signature one
at a time on one core (types/validator_set.go:685-707 → ed25519.go:148).
BASELINE.md:26-36 demands measurement against BOTH that serial loop and a
CPU *batch* baseline (64-sig batches through the BatchVerifier boundary —
note: the cpu backend's verify() is itself a serial per-sig loop, so this
measures boundary overhead, not batch math; the honest ≥20× denominator
is whichever CPU number is highest), plus VerifyCommit p50 at 150 and 10k
validators on both backends.

Staged preflight (each stage subprocess-isolated with its own timeout so a
wedged TPU runtime can never take the bench down with it):
  1. device enumerate                  (120 s)
  2. jit lower+compile, batch=64       (600 s)
  3. timed full run + sweep            (600 s)
  4. VerifyCommit p50s + merkle        (600 s)
  5. kernel variants: mul forms, device-hash, sharded mega-commit (600 s)
If a TPU stage fails, fall back to the same kernel on the virtual CPU
platform (the matmul mul form compiles there in ~20 s — measured 909 s
for shift_add, which is what zeroed round 3); if even that fails, the
measured CPU-serial number is reported so the value is NEVER 0.0. Every
stage's outcome is recorded in the "stages" field for diagnosability.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"vs_serial", "vs_best_cpu", "stages"}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 4096
SWEEP = (1024, 4096, 8192, 16384)
# inherit ambient (axon) platform; stage 1 (devices) already proves the
# tunnel answers, so the batch boundary's own subprocess probe is
# redundant inside later stages and would pollute p50 timings
_STAGE_ENV_TPU = {"CBFT_TPU_PROBE": "0"}
_STAGE_ENV_CPU = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_FORCE_CPU": "1",
}
# the sharded stage needs a multi-device plane; the virtual CPU mesh is
# how it runs hardware-free (same flag tier-1 CI uses)
_STAGE_ENV_SHARDED = {
    **_STAGE_ENV_CPU,
    "CBFT_TPU_PROBE": "0",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def _make_batch(n: int, msg_len: int = 120):
    from cometbft_tpu.crypto import ed25519 as ed

    rng = np.random.default_rng(42)
    keys = [
        ed.gen_priv_key_from_secret(bytes([i & 0xFF, i >> 8]))
        for i in range(min(n, 128))
    ]
    pks, msgs, sigs = [], [], []
    for i in range(n):
        k = keys[i % len(keys)]
        m = rng.bytes(msg_len)  # ~ a canonical vote's sign-bytes size
        pks.append(k.pub_key().bytes())
        msgs.append(m)
        sigs.append(k.sign(m))
    return pks, msgs, sigs


def _make_commit(n_vals: int):
    """A real Commit over n_vals validators + its ValidatorSet."""
    from cometbft_tpu.proto.gogo import Timestamp
    from cometbft_tpu.types import test_util

    vals, privs = test_util.deterministic_validator_set(n_vals, 10)
    bid = test_util.make_block_id()
    commit = test_util.make_commit(
        bid, 5, 0, vals, privs, "bench-chain", now=Timestamp(1_700_000_000, 0)
    )
    return vals, commit, bid


def bench_cpu_serial(n: int = 512) -> float:
    from cometbft_tpu.crypto import ed25519 as ed

    pks, msgs, sigs = _make_batch(n)
    keys = [ed.PubKeyEd25519(pk) for pk in pks]
    t0 = time.perf_counter()
    for k, m, s in zip(keys, msgs, sigs):
        assert k.verify_signature(m, s)
    dt = time.perf_counter() - t0
    return n / dt


def bench_cpu_parallel(n: int = 4096) -> float:
    """The upgraded CPU plane: ed25519.verify_many — one native
    multi-threaded call on multicore hosts, cached-handle tight loop on
    one core. This is the node's real fallback when the TPU tunnel is
    down (it wedged rounds 3 and 4)."""
    from cometbft_tpu.crypto import ed25519 as ed

    pks, msgs, sigs = _make_batch(n)
    items = [(ed.PubKeyEd25519(pk), m, s) for pk, m, s in zip(pks, msgs, sigs)]
    assert all(ed.verify_many(items))  # warm native build + key handles
    t0 = time.perf_counter()
    assert all(ed.verify_many(items))
    dt = time.perf_counter() - t0
    return n / dt


def bench_cpu_batch(n: int = 1024, batch_size: int = 64) -> float:
    """The BASELINE.md CPU batch baseline: 64-sig batches through the
    BatchVerifier boundary (cpu backend — a serial loop inside)."""
    from cometbft_tpu.crypto import batch as cryptobatch
    from cometbft_tpu.crypto import ed25519 as ed

    pks, msgs, sigs = _make_batch(n)
    keys = [ed.PubKeyEd25519(pk) for pk in pks]
    t0 = time.perf_counter()
    for start in range(0, n, batch_size):
        bv = cryptobatch.new_batch_verifier("cpu")
        for i in range(start, min(start + batch_size, n)):
            bv.add(keys[i], msgs[i], sigs[i])
        ok, _ = bv.verify()
        assert ok
    dt = time.perf_counter() - t0
    return n / dt


def bench_verify_commit_p50(n_vals: int, backend: str, reps: int) -> float:
    """VerifyCommit wall-time p50 (ms) at n_vals validators."""
    vals, commit, bid = _make_commit(n_vals)
    times = []
    # warmup (compile for the tpu backend)
    vals.verify_commit("bench-chain", bid, 5, commit, backend=backend)
    for _ in range(reps):
        t0 = time.perf_counter()
        vals.verify_commit("bench-chain", bid, 5, commit, backend=backend)
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2] * 1e3


def _time_verify_batch(pks, msgs, sigs, reps: int = 3) -> float:
    from cometbft_tpu.crypto.tpu import ed25519_batch

    res = ed25519_batch.verify_batch(pks, msgs, sigs)  # warmup/compile
    assert all(res), "benchmark batch must verify"
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        ed25519_batch.verify_batch(pks, msgs, sigs)
        best = min(best, time.perf_counter() - t0)
    return len(pks) / best


# ---------------------------------------------------------------------------
# subprocess stages (run with: python bench.py --stage <name>)
# ---------------------------------------------------------------------------


def _maybe_force_cpu():
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax

        # env vars alone are too late if sitecustomize pre-imported jax
        jax.config.update("jax_platforms", "cpu")


def _stage_devices():
    _maybe_force_cpu()
    import jax

    devs = jax.devices()
    print(json.dumps({"n": len(devs), "platform": devs[0].platform}))


def _stage_compile():
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto.tpu import ed25519_batch

    pks, msgs, sigs = _make_batch(64)
    t0 = time.perf_counter()
    out = ed25519_batch.verify_batch(pks, msgs, sigs)
    compile_and_run_s = time.perf_counter() - t0
    assert all(out), "preflight batch must verify"
    # emit before the split measurement: a hang on the second call must
    # not lose the compile number (last-parseable-line contract)
    print(
        json.dumps({"compile_and_run_s": round(compile_and_run_s, 2)}),
        flush=True,
    )
    # the second call reuses the warmed executable — pure execute time;
    # the difference is the compile cost (persistent-cache-aware: near
    # zero when .jax_cache already holds this shape)
    t0 = time.perf_counter()
    ed25519_batch.verify_batch(pks, msgs, sigs)
    execute_s = time.perf_counter() - t0
    print(
        json.dumps({
            "compile_and_run_s": round(compile_and_run_s, 2),
            "execute_s": round(execute_s, 3),
            "compile_s": round(max(compile_and_run_s - execute_s, 0.0), 2),
        }),
        flush=True,
    )


def _stage_run():
    _maybe_force_cpu()
    _set_cache()
    out = {}
    best_overall = 0.0
    sweep = SWEEP
    passes = 2
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # the fallback exists to guarantee A number: one modest shape,
        # compiled with the fast matmul mul form (field.default_mul_impl)
        sweep = (1024,)
        passes = 1
    # Two sweep passes with a gap, per-size max: the tunneled link's
    # throughput varies ~15x between minute-scale windows (measured
    # 4.5k vs 69k sigs/s within one session), and min-of-3 reps inside
    # one window cannot see past it. The inter-pass pause pushes pass 2
    # into a different window; a slow window must now last the whole
    # stage to poison the headline. If the pause+pass 2 overruns the
    # stage timeout, the incremental emits preserve pass 1's numbers.
    batches = {batch: _make_batch(batch) for batch in sweep}
    for pass_idx in range(passes):
        if pass_idx:
            time.sleep(45)
        for batch in sweep:
            rate = _time_verify_batch(*batches[batch])
            out[str(batch)] = max(out.get(str(batch), 0.0), round(rate, 1))
            best_overall = max(best_overall, rate)
            # emit incrementally: a timeout mid-sweep still leaves numbers
            print(
                json.dumps({"sigs_per_sec": best_overall, "sweep": out}),
                flush=True,
            )


def _stage_scheduler():
    """Coalesced vs per-caller dispatch throughput. N concurrent callers
    each hold a sub-floor 64-sig request: per_caller mode builds one
    BatchVerifier per request (N separate backend dispatches); coalesced
    mode submits the same requests to one VerifyScheduler, whose
    deadline/lane-budget flush folds them into fewer, larger dispatches
    routed on the COALESCED size."""
    import threading

    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto import batch as cryptobatch
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto.scheduler import VerifyScheduler

    backend = "cpu" if os.environ.get("BENCH_FORCE_CPU") == "1" else "tpu"
    n_callers, per_caller = 4, 64
    reqs = [
        [
            (ed.PubKeyEd25519(pk), m, s)
            for pk, m, s in zip(*_make_batch(per_caller))
        ]
        for _ in range(n_callers)
    ]
    n_sigs = n_callers * per_caller

    def fanout(fn):
        errs = []

        def wrap(i):
            try:
                fn(i)
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        ts = [
            threading.Thread(target=wrap, args=(i,))
            for i in range(n_callers)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        dt = time.perf_counter() - t0
        if errs:
            raise errs[0]
        return dt

    def per_caller_verify(i):
        bv = cryptobatch.new_batch_verifier(backend)
        for pk, m, s in reqs[i]:
            bv.add(pk, m, s)
        ok, _ = bv.verify()
        assert ok

    per_caller_verify(0)  # warm the kernel: neither mode pays compile
    dt_per_caller = min(fanout(per_caller_verify) for _ in range(3))
    out = {"per_caller_sigs_per_sec": round(n_sigs / dt_per_caller, 1)}
    print(json.dumps(out), flush=True)

    sched = VerifyScheduler(spec=backend)
    sched.start()
    try:

        def coalesced_verify(i):
            ok, _ = sched.submit(reqs[i]).result(timeout=120)
            assert ok

        dt_coalesced = min(fanout(coalesced_verify) for _ in range(3))
        out["coalesced_sigs_per_sec"] = round(n_sigs / dt_coalesced, 1)
        out["scheduler_dispatches"] = sched.n_dispatches
        out["per_caller_dispatches"] = 3 * n_callers
    finally:
        sched.stop()
    print(json.dumps(out), flush=True)


def _stage_trace():
    """Verify-path tracing overhead + per-stage attribution. Runs the
    scheduler-stage workload (4 concurrent 64-sig callers) twice through
    identical VerifySchedulers — tracing disabled (sample=0, the no-op
    span fast path) vs fully sampled (sample=1) — and reports the
    throughput delta. The disabled-mode budget is < 3%: the stage exits
    non-zero past it, so a regression that puts real work on the
    untraced hot path fails the bench loudly. Also embeds the per-stage
    breakdown of one fully-traced dispatch (request/dispatch/supervise/
    cpu|device/chunk durations) — the attribution numbers the trace
    layer exists to produce."""
    import threading

    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto.scheduler import VerifyScheduler
    from cometbft_tpu.libs import trace as tracelib

    backend = "cpu" if os.environ.get("BENCH_FORCE_CPU") == "1" else "tpu"
    n_callers, per_caller = 4, 64
    reqs = [
        [
            (ed.PubKeyEd25519(pk), m, s)
            for pk, m, s in zip(*_make_batch(per_caller))
        ]
        for _ in range(n_callers)
    ]
    n_sigs = n_callers * per_caller

    def fanout(sched):
        errs = []

        def wrap(i):
            try:
                ok, _ = sched.submit(reqs[i]).result(timeout=120)
                assert ok
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        ts = [
            threading.Thread(target=wrap, args=(i,))
            for i in range(n_callers)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        dt = time.perf_counter() - t0
        if errs:
            raise errs[0]
        return dt

    def throughput(tracer, reps=5):
        sched = VerifyScheduler(spec=backend, tracer=tracer)
        sched.start()
        try:
            fanout(sched)  # warm (kernel + threads), untimed
            return n_sigs / min(fanout(sched) for _ in range(reps))
        finally:
            sched.stop()

    off = throughput(tracelib.Tracer(sample=0.0))
    traced = tracelib.Tracer(sample=1.0, buffer=256)
    on = throughput(traced)
    overhead_pct = max(0.0, (off - on) / off * 100.0) if off else 0.0

    # per-stage breakdown of one traced dispatch: the newest trace that
    # actually carried a dispatch span (coalesced siblings carry only
    # their request span)
    breakdown = {}
    for tr in traced.recent():
        names = {sp["name"] for sp in tr["spans"]}
        if "dispatch" in names:
            breakdown = {
                sp["name"]: round(sp["dur_us"], 1) for sp in tr["spans"]
            }
            break

    out = {
        "untraced_sigs_per_sec": round(off, 1),
        "traced_sigs_per_sec": round(on, 1),
        "tracing_overhead_pct": round(overhead_pct, 2),
        "dispatch_breakdown_us": breakdown,
        "traces_recorded": len(traced.recent()),
    }
    # emit BEFORE the budget check so a failure still carries numbers
    print(json.dumps(out), flush=True)
    assert overhead_pct <= 3.0, (
        f"tracing overhead {overhead_pct:.2f}% (sampled vs off) exceeds "
        f"the 3% budget on the scheduler stage "
        f"(off={off:.1f} on={on:.1f} sigs/s)"
    )


def _stage_p50():
    _maybe_force_cpu()
    _set_cache()
    out = {}
    backend = "cpu" if os.environ.get("BENCH_FORCE_CPU") == "1" else "tpu"
    out[f"verify_commit_p50_ms_150_{backend}"] = round(
        bench_verify_commit_p50(150, backend, reps=9), 2
    )
    print(json.dumps(out), flush=True)
    out[f"verify_commit_p50_ms_10k_{backend}"] = round(
        bench_verify_commit_p50(10_000, backend, reps=3), 2
    )
    print(json.dumps(out), flush=True)
    # 10k-validator mega-set Merkle root (ValidatorSet.Hash)
    from cometbft_tpu.types import test_util

    vals, _ = test_util.deterministic_validator_set(10_000, 10)
    items = [v.bytes() for v in vals.validators]
    if backend == "tpu":
        from cometbft_tpu.crypto.tpu import merkle as tpu_merkle

        tpu_merkle.hash_from_byte_slices(items, force_device=True)  # warm
        t0 = time.perf_counter()
        tpu_merkle.hash_from_byte_slices(items, force_device=True)
        out["merkle_10k_root_ms_tpu"] = round((time.perf_counter() - t0) * 1e3, 2)
        print(json.dumps(out), flush=True)
    from cometbft_tpu.crypto import merkle as cpu_merkle

    t0 = time.perf_counter()
    cpu_merkle.hash_from_byte_slices(items)
    out["merkle_10k_root_ms_cpu"] = round((time.perf_counter() - t0) * 1e3, 2)
    print(json.dumps(out), flush=True)


def _stage_variants():
    """A/B matrix on the live platform: CBFT_TPU_MUL forms, device-side
    hashing, and the shard_map mega-commit (VERDICT r3 items 2/5)."""
    _maybe_force_cpu()
    _set_cache()
    import jax

    out = {}
    batch = _make_batch(4096)
    for mul in ("shift_add", "matmul", "stack", "f32"):
        os.environ["CBFT_TPU_MUL"] = mul
        # fe.mul reads the env var at TRACE time; without this the later
        # variants would silently reuse the first variant's executable
        jax.clear_caches()
        try:
            out[f"mul_{mul}_sigs_per_sec"] = round(_time_verify_batch(*batch), 1)
        except Exception as exc:  # noqa: BLE001
            out[f"mul_{mul}_sigs_per_sec"] = f"error: {exc}"[:120]
        print(json.dumps(out), flush=True)
    os.environ.pop("CBFT_TPU_MUL", None)
    jax.clear_caches()
    os.environ["CBFT_TPU_HASH"] = "device"
    try:
        out["device_hash_sigs_per_sec"] = round(_time_verify_batch(*batch), 1)
    except Exception as exc:  # noqa: BLE001
        out["device_hash_sigs_per_sec"] = f"error: {exc}"[:120]
    os.environ.pop("CBFT_TPU_HASH", None)
    print(json.dumps(out), flush=True)
    try:
        out["sharded_10k_commit"] = _sharded_mega_commit()
    except Exception as exc:  # noqa: BLE001
        out["sharded_10k_commit"] = f"error: {exc}"[:160]
    print(json.dumps(out), flush=True)
    # resident valset rows vs the per-batch wire, same 8192 lanes: the
    # resident path ships 96 B/sig (R|S|h) against 128 B/sig and reuses
    # one fixed executable — the per-height commit shape
    # (cometbft_tpu/crypto/tpu/ed25519_batch.py verify_valset_resident)
    try:
        import hashlib as _hl

        from cometbft_tpu.crypto.tpu import ed25519_batch as _eb

        pks, msgs, sigs = _make_batch(8192)
        t_batch = _time_verify_batch(pks, msgs, sigs)
        vid = _hl.sha256(b"".join(pks)).digest()
        res = _eb.verify_valset_resident(vid, pks, msgs, sigs)  # build+compile
        assert all(res), "resident benchmark batch must verify"
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            _eb.verify_valset_resident(vid, pks, msgs, sigs)
            best = min(best, time.perf_counter() - t0)
        out["resident_8192_sigs_per_sec"] = round(len(pks) / best, 1)
        out["perbatch_8192_sigs_per_sec"] = round(t_batch, 1)
    except Exception as exc:  # noqa: BLE001
        out["resident_8192_sigs_per_sec"] = f"error: {exc}"[:120]
    print(json.dumps(out), flush=True)


def _stage_breakdown():
    """Where a batch-4096 verify spends its time: host packing (incl.
    SHA-512 in host-hash mode), host→device transfer, and device compute
    split into decompress+table vs the Straus loop (jitted separately),
    for both the legacy u32 word wire and the compact uint8 wire.
    The separated pieces don't add exactly to the fused kernel (fusion
    across the split is lost) but bound each phase honestly. Every
    stage reports the MEDIAN of 5 timed reps (after a warm rep): a
    single-run sample at the ~0.1 ms scale jittered enough to report a
    negative Straus-loop estimate in round 5, so the derived loop time
    is a clamped-at-zero difference of medians."""
    _maybe_force_cpu()
    _set_cache()
    import statistics

    import jax
    import jax.numpy as jnp

    from cometbft_tpu.crypto.tpu import ed25519_batch as eb

    def med_ms(fn, reps=5):
        fn()  # warm: compile / first-touch
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            times.append((time.perf_counter() - t0) * 1e3)
        return round(statistics.median(times), 2)

    out = {}
    pks, msgs, sigs = _make_batch(4096)
    n = len(pks)

    out["host_prepare_ms"] = med_ms(
        lambda: eb.prepare_batch(pks, msgs, sigs)
    )
    out["host_prepare_compact_ms"] = med_ms(
        lambda: eb.prepare_batch_compact(pks, msgs, sigs)
    )
    print(json.dumps(out), flush=True)

    (*packed, _valid) = eb.prepare_batch(pks, msgs, sigs)
    (wire_c, _valid_c) = eb.prepare_batch_compact(pks, msgs, sigs)
    out["wire_bytes_per_lane"] = round(
        sum(a.nbytes for a in packed) / n, 1
    )
    out["compact_wire_bytes_per_lane"] = round(wire_c.nbytes / n, 1)
    out["transfer_ms"] = med_ms(
        lambda: jax.block_until_ready(
            [jax.device_put(jnp.asarray(a)) for a in packed]
        )
    )
    out["transfer_compact_ms"] = med_ms(
        lambda: jax.block_until_ready(
            jax.device_put(jnp.asarray(wire_c))
        )
    )
    print(json.dumps(out), flush=True)

    dev = [jax.device_put(jnp.asarray(a)) for a in packed]
    dev_c = jax.device_put(jnp.asarray(wire_c))

    @jax.jit
    def decompress_and_table(wire):
        ay, a_sign, _r_y, _r_sign, _s, _h = eb.unpack_wire(wire)
        x, ok = eb.decompress(ay, a_sign)
        nx = eb.fe.neg(x)
        neg_a = (nx, ay, jnp.broadcast_to(eb._ONE_FE, ay.shape), eb.fe.mul(nx, ay))
        a2 = eb.point_dbl(neg_a)
        a3 = eb.point_add(a2, neg_a)
        return ok, a2[0], a3[0]

    (wire,) = dev
    med_decomp = med_ms(
        lambda: jax.block_until_ready(decompress_and_table(wire))
    )
    out["device_decompress_table_ms"] = med_decomp
    print(json.dumps(out), flush=True)

    med_full = med_ms(
        lambda: jax.block_until_ready(eb.verify_kernel(*dev))
    )
    out["device_full_kernel_ms"] = med_full
    out["device_full_kernel_compact_ms"] = med_ms(
        lambda: jax.block_until_ready(eb.verify_kernel_compact(dev_c))
    )
    # clamped difference of medians: the two programs are jitted
    # separately, so at TPU speeds the subtraction can go (slightly)
    # negative — that means "decompress-dominated", not negative time
    out["device_straus_loop_ms_est"] = round(
        max(0.0, med_full - med_decomp), 2
    )
    print(json.dumps(out), flush=True)

    # device-hash pipeline, called explicitly (no env gating needed)
    out["host_prepare_devicehash_ms"] = med_ms(
        lambda: eb.prepare_batch_device_hash(pks, msgs, sigs)
    )
    out["host_prepare_devicehash_compact_ms"] = med_ms(
        lambda: eb.prepare_batch_device_hash_compact(pks, msgs, sigs)
    )
    (*packed_dh, _valid) = eb.prepare_batch_device_hash(pks, msgs, sigs)
    wire_dc, msg_dc, mlen_dc, _valid = eb.prepare_batch_device_hash_compact(
        pks, msgs, sigs
    )
    out["devicehash_wire_bytes_per_lane"] = round(
        sum(a.nbytes for a in packed_dh) / n, 1
    )
    out["devicehash_compact_wire_bytes_per_lane"] = round(
        (wire_dc.nbytes + msg_dc.nbytes + mlen_dc.nbytes) / n, 1
    )
    dev_dh = [jax.device_put(jnp.asarray(a)) for a in packed_dh]
    out["device_full_kernel_devicehash_ms"] = med_ms(
        lambda: jax.block_until_ready(eb.verify_full_kernel(*dev_dh))
    )
    dev_dc = [
        jax.device_put(jnp.asarray(a)) for a in (wire_dc, msg_dc, mlen_dc)
    ]
    out["device_full_kernel_devicehash_compact_ms"] = med_ms(
        lambda: jax.block_until_ready(
            eb.verify_full_kernel_compact(*dev_dc)
        )
    )
    print(json.dumps(out), flush=True)


def _sharded_mega_commit():
    """10k-signature commit verification sharded over every available
    device via explicit NamedSharding on the batch (lane) axis — the
    SURVEY §7 stage-10 mega-commit. On the single-chip tunnel this runs
    1-way; under XLA_FLAGS=--xla_force_host_platform_device_count=8 it
    validates the 8-way program (MULTICHIP artifact covers compile;
    this stage records measured timing)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

    from cometbft_tpu.crypto.tpu import ed25519_batch

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("batch",))
    n = 10_000
    pad = 10_240  # multiple of 8 devices × 128 lanes
    pks, msgs, sigs = _make_batch(n)
    (*packed, valid) = ed25519_batch.prepare_batch(pks, msgs, sigs)
    assert valid.all()

    def pad_to(a):
        out = np.zeros(a.shape[:-1] + (pad,), a.dtype)
        out[..., :n] = a
        return out

    shardings = tuple(
        NamedSharding(mesh, PS(*([None] * (a.ndim - 1) + ["batch"])))
        for a in packed
    )
    step = jax.jit(
        ed25519_batch._verify_core,
        in_shardings=shardings,
        out_shardings=NamedSharding(mesh, PS("batch")),
    )
    args = [
        jax.device_put(jnp.asarray(pad_to(a)), s)
        for a, s in zip(packed, shardings)
    ]
    with mesh:
        mask = np.asarray(step(*args))  # compile + warm
        assert mask[:n].all()
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(step(*args))
            best = min(best, time.perf_counter() - t0)
    return {
        "n_devices": len(devs),
        "per_device_batch": pad // len(devs),
        "ms": round(best * 1e3, 2),
        "sigs_per_sec": round(n / best, 1),
    }


def _stage_sharded():
    """Sharded-megabatch routing stage: a 10k-commit megabatch through
    the PRODUCTION dispatch path — shard plan over the topology, AOT
    registry, per-device chunk caps, NamedSharding on the batch axis —
    once pinned single-chip and once sharded over the full mesh (the
    two routes the scheduler picks between at the learned crossover).
    Unlike _sharded_mega_commit (a hand-jitted program), this measures
    what a routed flush actually runs. Emits incrementally so a timeout
    keeps the single-chip number."""
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto.tpu import ed25519_batch, mesh, topology

    topo = topology.DeviceTopology.detect()
    topology.set_default_topology(topo)
    plan = mesh.shard_plan(topo)
    n = int(os.environ.get("BENCH_SHARDED_N", "10000"))
    pks, msgs, sigs = _make_batch(n)
    out = {
        "n": n,
        "n_devices": len(topo),
        "shards": plan.n_shards if plan is not None else 1,
    }
    # meta first: a timeout mid-compile still leaves a parseable record
    print(json.dumps(out), flush=True)

    def best_rate(route, reps=3):
        with mesh.route_scope(route):
            mask = ed25519_batch.verify_batch(pks, msgs, sigs)  # warm
            assert all(mask), "mega-commit must verify"
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                ed25519_batch.verify_batch(pks, msgs, sigs)
                best = min(best, time.perf_counter() - t0)
        return n / best

    out["single_chip_sigs_per_sec"] = round(best_rate(mesh.ROUTE_SINGLE), 1)
    print(json.dumps(out), flush=True)
    if plan is not None:
        out["sharded_sigs_per_sec"] = round(best_rate(mesh.ROUTE_SHARDED), 1)
        out["sharded_vs_single"] = round(
            out["sharded_sigs_per_sec"] / out["single_chip_sigs_per_sec"], 3
        ) if out["single_chip_sigs_per_sec"] else 0.0
    else:
        out["sharded_unavailable"] = "fewer than 2 healthy devices"
    print(json.dumps(out), flush=True)


def _stage_supervisor():
    """Degraded-mode throughput + breaker recovery latency. A supervised
    FaultyBackend is driven healthy → broken (injected dispatch
    failures) → repaired: the stage reports verify throughput in each
    breaker state (broken mode = the zero-added-latency CPU route) and
    the wall-clock from fault clearance to breaker re-close (canary
    probe re-admission)."""
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto.batch import BackendSpec
    from cometbft_tpu.crypto.faults import FaultPlan, install
    from cometbft_tpu.crypto.supervisor import BROKEN, HEALTHY, BackendSupervisor

    plan = install(name="bench-faulty", inner="cpu", plan=FaultPlan())
    sup = BackendSupervisor(
        spec=BackendSpec("bench-faulty"),
        dispatch_timeout_ms=2000,
        breaker_threshold=1,
        audit_pct=0,
        probe_base_ms=25,
        probe_max_ms=200,
    )
    n = 1024
    pks, msgs, sigs = _make_batch(n)
    items = [
        (ed.PubKeyEd25519(pk), m, s) for pk, m, s in zip(pks, msgs, sigs)
    ]

    def rate() -> float:
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            mask = sup.verify_items(items)
            best = min(best, time.perf_counter() - t0)
            assert all(mask)
        return round(n / best, 1)

    out = {"healthy_sigs_per_sec": rate()}
    assert sup.state() == HEALTHY
    print(json.dumps(out), flush=True)

    # one failing dispatch trips the threshold-1 breaker
    plan.exception_rate = 1.0
    sup.verify_items(items)
    assert sup.state() == BROKEN, sup.state()
    out["broken_sigs_per_sec"] = rate()  # the straight-to-CPU route
    print(json.dumps(out), flush=True)

    # recovery latency: faults cleared → canary probes re-admit
    plan.clear()
    t0 = time.perf_counter()
    deadline = t0 + 60.0
    while sup.state() != HEALTHY and time.perf_counter() < deadline:
        sup.verify_items(items[:1])  # traffic kicks the lazy async probe
        time.sleep(0.005)
    recovered = sup.state() == HEALTHY
    out["breaker_recovery_ms"] = (
        round((time.perf_counter() - t0) * 1e3, 1) if recovered
        else "not recovered within 60s"
    )
    out["final_state"] = sup.state()
    sup.stop()
    print(json.dumps(out), flush=True)


def _stage_degraded():
    """Degradation-ladder numbers (adaptive dispatch, crypto/supervisor):
    (1) supervised throughput under CBFT_FAULT_TRANSIENT_N=2 + a 5%%
    latency-jitter fault must stay within 2x of the healthy-path number
    (the retry rung absorbs the flaps instead of stalling on the
    watchdog); (2) a mixed-verdict 8k batch with 8 bad signatures is
    triaged in <= ceil(log2(8192))+1 device passes (asserted from the
    dispatch-count metrics); (3) the deterministic chaos smoke reports
    zero verdict divergence vs the serial CPU ground truth."""
    _maybe_force_cpu()
    _set_cache()
    import math

    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto.batch import BackendSpec
    from cometbft_tpu.crypto.faults import (
        FaultPlan, install, run_chaos_smoke,
    )
    from cometbft_tpu.crypto.supervisor import BackendSupervisor
    from cometbft_tpu.crypto.tpu import mesh

    plan = install(name="bench-degraded", inner="cpu", plan=FaultPlan())
    sup = BackendSupervisor(
        spec=BackendSpec("bench-degraded"),
        dispatch_timeout_ms=10_000,
        breaker_threshold=3,
        audit_pct=0,
        probe_base_ms=25,
        probe_max_ms=200,
        retry_ms=5,
    )
    n = 1024
    pks, msgs, sigs = _make_batch(n)
    items = [
        (ed.PubKeyEd25519(pk), m, s) for pk, m, s in zip(pks, msgs, sigs)
    ]
    rounds = 6

    def rate() -> float:
        # aggregate (not best-of) throughput: the degraded window's
        # retries/fallbacks must COUNT, that is the measurement
        t0 = time.perf_counter()
        for _ in range(rounds):
            mask = sup.verify_items(items)
            assert all(mask)
        return round(rounds * n / (time.perf_counter() - t0), 1)

    out = {"healthy_sigs_per_sec": rate()}
    print(json.dumps(out), flush=True)

    # degraded window: first 2 dispatches flap (UNAVAILABLE) the way
    # CBFT_FAULT_TRANSIENT_N=2 injects, plus ~5% uniform latency jitter
    healthy_dispatch_ms = rounds * n / out["healthy_sigs_per_sec"] / rounds * 1e3
    plan.transient_n = int(os.environ.get("CBFT_FAULT_TRANSIENT_N", "2"))
    plan.jitter_ms = max(0.5, 0.05 * healthy_dispatch_ms)
    out["degraded_sigs_per_sec"] = rate()
    plan.clear()
    slowdown = out["healthy_sigs_per_sec"] / max(
        out["degraded_sigs_per_sec"], 1e-9
    )
    out["degraded_slowdown_x"] = round(slowdown, 3)
    out["degraded_within_2x"] = slowdown <= 2.0
    print(json.dumps(out), flush=True)

    # triage localization: 8k lanes, 8 bad signatures — count the device
    # passes the bisection needs (dispatch-count metrics, not wall clock)
    big_n = 8192
    pks, msgs, sigs = _make_batch(big_n)
    big = [
        (ed.PubKeyEd25519(pk), m, s) for pk, m, s in zip(pks, msgs, sigs)
    ]
    truth = [True] * big_n
    for lane in range(0, big_n, big_n // 8):
        big[lane] = (big[lane][0], big[lane][1], b"\x17" * 64)
        truth[lane] = False
    before = sup.metrics.device_dispatches.value()
    t0 = time.perf_counter()
    mask = sup.verify_items(big, reason="bench-triage")
    triage_ms = round((time.perf_counter() - t0) * 1e3, 1)
    passes = int(sup.metrics.device_dispatches.value() - before) - 1
    bound = math.ceil(math.log2(big_n)) + 1
    out["triage"] = {
        "n_sigs": big_n,
        "n_bad": 8,
        "device_passes": passes,
        "pass_bound": bound,
        "within_bound": passes <= bound,
        "verdicts_match_ground_truth": mask == truth,
        "ms": triage_ms,
    }
    sup.stop()
    mesh.reset_chunk_shrink()
    print(json.dumps(out), flush=True)

    # ladder smoke: every rung walked once, zero divergence required
    smoke = run_chaos_smoke(seed=11)
    out["chaos_smoke"] = {
        "wrong_verdicts": smoke["wrong_verdicts"],
        "hedge_divergence": smoke["hedge_divergence"],
        "triage_divergence": smoke["triage_divergence"],
        "rungs_walked": bool(
            smoke["retries"] >= 1
            and smoke["chunk_shrinks"] >= 1
            and smoke["hedge_fires"] >= 1
            and smoke["triage_runs"] >= 1
            and smoke["state_final"] == smoke["expected"]["state_final"]
        ),
    }
    print(json.dumps(out), flush=True)

    # partial degradation: an N-virtual-domain mesh with one domain
    # dead must keep >= 0.6 x (N-1)/N of its own healthy rate ON THE
    # DEVICE PATH — quarantine + batch-axis redistribution over the
    # survivors, never a node-wide CPU fallback — and the verdicts of
    # a mixed batch must equal the serial CPU ground truth throughout
    from cometbft_tpu.crypto.tpu import topology as topolib

    ndev, kill = 4, 2
    topo = topolib.DeviceTopology.virtual(ndev)
    plan2 = install(
        name="bench-partial", inner="cpu", plan=FaultPlan(device=kill)
    )
    sup2 = BackendSupervisor(
        spec=BackendSpec("bench-partial"),
        dispatch_timeout_ms=10_000,
        breaker_threshold=1,
        audit_pct=0,
        hedge_pct=0,
        # quarantine must hold for the whole degraded window: push the
        # async canary backoff far past the stage timeout
        probe_base_ms=300_000,
        probe_max_ms=600_000,
        retry_ms=5,
        topology=topo,
    )

    def rate2() -> float:
        t0 = time.perf_counter()
        for _ in range(rounds):
            mask2 = sup2.verify_items(items)
            assert all(mask2)
        return round(rounds * n / (time.perf_counter() - t0), 1)

    part = {"n_domains": ndev, "killed": f"dev{kill}"}
    part["healthy_sigs_per_sec"] = rate2()

    # kill domain 2: its first shard fails, trips its breaker, and the
    # batch axis redistributes over the three survivors
    plan2.exception_rate = 1.0
    mask2 = sup2.verify_items(items, reason="bench-partial-trip")
    assert all(mask2)
    part["killed_state"] = sup2.device_states()[f"dev{kill}"]

    cpu_before = sup2.metrics.cpu_routed.value()
    dev_before = sup2.metrics.device_dispatches.value()
    part["degraded_sigs_per_sec"] = rate2()
    part["cpu_routed_while_degraded"] = int(
        sup2.metrics.cpu_routed.value() - cpu_before
    )
    part["device_dispatches_while_degraded"] = int(
        sup2.metrics.device_dispatches.value() - dev_before
    )

    # verdict parity under partial degradation: 8 bad lanes, ground
    # truth from the batch construction
    mixed = list(items)
    truth2 = [True] * n
    for lane in range(0, n, n // 8):
        mixed[lane] = (mixed[lane][0], mixed[lane][1], b"\x17" * 64)
        truth2[lane] = False
    part["verdicts_match_ground_truth"] = (
        sup2.verify_items(mixed, reason="bench-partial-mixed") == truth2
    )

    floor = 0.6 * (ndev - 1) / ndev
    ratio = part["degraded_sigs_per_sec"] / max(
        part["healthy_sigs_per_sec"], 1e-9
    )
    part["throughput_ratio"] = round(ratio, 3)
    part["floor"] = round(floor, 3)
    part["above_floor"] = ratio >= floor
    part["survivors_kept_device_path"] = (
        part["cpu_routed_while_degraded"] == 0
        and part["device_dispatches_while_degraded"] > 0
    )
    out["partial_degraded"] = part
    plan2.clear()
    sup2.stop()
    print(json.dumps(out), flush=True)



def _stage_overload():
    """QoS overload numbers (crypto/qos, crypto/scheduler admission
    layer): the chaos overload rung's latency picture as bench evidence
    — unloaded vs loaded consensus p99 with the class ladder on, the
    same flood's consensus p99 with CBFT_QOS_CLASSES=off, and the
    shed/drop/brownout counters. The headline booleans (latency bound
    held, floods shed, brownout tripped and re-admitted, FIFO starved)
    ride along so the history ledger records pass/fail, not just
    milliseconds."""
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto.faults import run_chaos_overload

    s = run_chaos_overload(seed=int(os.environ.get("CBFT_BENCH_SEED", "17")))
    out = {
        "unloaded_p99_ms": s["unloaded_p99_ms"],
        "loaded_p99_ms": s["loaded_p99_ms"],
        "latency_bound_ms": s["latency_bound_ms"],
        "latency_ok": s["latency_ok"],
        "qos_off_p99_ms": s["qos_off_p99_ms"],
        "starvation_ratio": s["starvation_ratio"],
        "starved_without_qos": s["starved_without_qos"],
        "flood_sheds": s["flood_sheds"],
        "flood_drops": s["flood_drops"],
        "consensus_sheds": s["consensus_sheds"],
        "consensus_drops": s["consensus_drops"],
        "brownout_trips": s["brownout"]["trips"],
        "brownout_readmissions": s["brownout"]["readmissions"],
        "readmitted": s["readmitted"],
        "wrong_verdicts": s["wrong_verdicts"],
    }
    print(json.dumps(out), flush=True)


def _stage_adversary():
    """Adversarial-committee numbers (crypto/adversary.py): the
    committee-size ladder (128 -> 1k validators) under a 25% byzantine
    vote flood with churn, equivocation bursts, and spam — p50/p99
    commit-verify per committee size while the storm rages, plus the
    zero-wrong-verdict and exact-attribution gates as booleans so the
    history ledger records pass/fail, not just milliseconds. The
    ``adversary_<n>_p99_ms`` / ``adversary_wrong_verdicts`` leaves ride
    the regression sentinel (tools/bench_history.py direction rules)."""
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto.adversary import run_adversary_ladder

    s = run_adversary_ladder(
        seed=int(os.environ.get("CBFT_BENCH_SEED", "17")),
        sizes=(128, 512, 1024),
        heights=6,
    )
    out = {"adversary_ok": s["ok"], "adversary_wrong_verdicts": 0}
    for n, r in s["rungs"].items():
        out["adversary_wrong_verdicts"] += r["wrong_verdicts"]
        out[f"adversary_{n}_p50_ms"] = r["loaded_p50_ms"]
        out[f"adversary_{n}_p99_ms"] = r["loaded_p99_ms"]
        out[f"adversary_{n}_unloaded_p99_ms"] = r["unloaded_p99_ms"]
        out[f"adversary_{n}_latency_ok"] = r["latency_ok"]
        out[f"adversary_{n}_offenders_exact"] = r["offenders_exact"]
    print(json.dumps(out), flush=True)


def _stage_ha():
    """HA verify-fleet numbers (crypto/faults.py run_chaos_ha): three
    replicated verifyd daemons under committee load through a rolling
    drain-restart, a hard kill, a socket blackhole, and a wrong-key
    client. The leaves that ride the regression sentinel: the failover
    verdict gap p99 (``ha_failover_gap_ms``, lower is better), the
    zero-CPU proof for the rolling restart
    (``ha_rolling_cpu_fallbacks``), the zero-wrong-verdict gate, and the
    fleet-vs-single aggregate throughput. ``ha_fleet_gain`` is recorded
    informationally — a single daemon's cross-client coalescing can
    legitimately beat a 3-way fleet split on a small box."""
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto.faults import run_chaos_ha

    s = run_chaos_ha(seed=int(os.environ.get("CBFT_BENCH_SEED", "17")))
    out = {
        "ha_replicas": s["replicas"],
        "ha_wrong_verdicts": s["wrong_verdicts"],
        "ha_failover_gap_ms": s["failover_gap_p99_ms"],
        "ha_rolling_failovers": s["rolling_failovers"],
        "ha_rolling_cpu_fallbacks": s["rolling_cpu_fallbacks"],
        "ha_rolling_readmits": s["rolling_readmits"],
        "ha_kill_failovers": s["kill_failovers"],
        "ha_blackhole_quarantined": s["blackhole_quarantined"],
        "ha_quarantine_picks_leaked": s["quarantine_picks_leaked"],
        "ha_probe_readmitted": s["probe_readmitted"],
        "ha_evil_unauthorized": s["evil_unauthorized"],
        "ha_evil_requests_served": s["evil_requests_served"],
        "ha_fleet_sigs_per_sec": s["fleet_sigs_per_sec"],
        "ha_single_sigs_per_sec": s["single_sigs_per_sec"],
        "ha_fleet_gain": s["fleet_gain"],
    }
    print(json.dumps(out), flush=True)


def _stage_decisions():
    """Decision-plane accuracy numbers (crypto/decisions.py): a warm
    verify workload through a scheduler with the routing ledger
    installed, then the ledger's own report card — per-(route, bucket)
    prediction MAPE (the ISSUE-15 acceptance bound is <= 0.5 for every
    profile with >= 5 observations), windowed regret, and the exact
    reconciliation of ledger decision counts against the scheduler's
    route counters. When CBFT_DECISIONS_SNAP names a path, a
    verify_top-shaped snapshot lands there for tools/route_audit.py."""
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto import decisions as declib
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto import wire as wirelib
    from cometbft_tpu.crypto.batch import BackendSpec
    from cometbft_tpu.crypto.scheduler import VerifyScheduler

    wire_ledger = wirelib.WireLedger()
    prev_wire = wirelib.set_default_ledger(wire_ledger)
    ledger = declib.DecisionLedger(
        cost_profile=wire_ledger.cost_profile()
    )
    prev = declib.set_default_ledger(ledger)
    sched = VerifyScheduler(spec=BackendSpec("cpu"), flush_us=300)
    sched.start()
    try:
        pks, msgs, sigs = _make_batch(256)
        items = [
            (ed.PubKeyEd25519(pk), m, s)
            for pk, m, s in zip(pks, msgs, sigs)
        ]
        # warm: absorb any one-time import/compile wall before the
        # ledger's cost model starts converging on steady-state cost
        sched.submit(items[:64], subsystem="bench").result(timeout=60)
        # two pow2 buckets, well past the >= 5-observation floor each
        for _ in range(12):
            ok, mask = sched.submit(
                items[:64], subsystem="bench"
            ).result(timeout=60)
            assert ok and all(mask)
            ok, mask = sched.submit(
                items, subsystem="bench"
            ).result(timeout=60)
            assert ok and all(mask)
        dsnap = ledger.snapshot()
        qsnap = sched.queue_snapshot()
    finally:
        sched.stop()
        declib.set_default_ledger(prev)
        wirelib.set_default_ledger(prev_wire)

    profiles = [
        p for p in dsnap["profiles"]
        if p["n"] >= 5 and p["mape"] is not None
    ]
    worst = max((p["mape"] for p in profiles), default=None)
    counts, routes = dsnap["counts"], qsnap["routes"]
    reconciled = all(
        counts.get(r, 0) == routes.get(r, 0)
        for r in set(counts) | set(routes)
    )
    snap_path = os.environ.get("CBFT_DECISIONS_SNAP")
    if snap_path:
        with open(snap_path, "w", encoding="utf-8") as f:
            json.dump(
                # "slo" marks the document a /debug/verify snapshot for
                # verify_top.load_snapshot; the bench has no SLO plane
                {
                    "slo": {},
                    "sources": {"decisions": dsnap, "scheduler": qsnap},
                },
                f, default=str,
            )
    # live-router acceptance gate (ISSUE 16): route_audit's own
    # --assert-live judgement over the snapshot this stage just built —
    # every priced-tagged decision took its feasible argmin and any
    # rollback carries a justifying cause. The audit tool IS the gate;
    # the bench only runs it.
    from tools import route_audit

    live_problems = route_audit.assert_live(dsnap, qsnap)
    assert not live_problems, f"route_audit --assert-live: {live_problems}"
    out = {
        "decisions": sum(counts.values()),
        "profiles_scored": len(profiles),
        "decisions_worst_mape": round(worst, 4) if worst is not None
        else None,
        "decisions_regret_ms": dsnap["windowed"]["regret_ms"],
        "regret_rate": dsnap["windowed"]["regret_rate"],
        "mape_ok": bool(profiles) and all(
            p["mape"] <= 0.5 for p in profiles
        ),
        "reconciled": reconciled,
        "route_audit_live_ok": not live_problems,
    }
    print(json.dumps(out), flush=True)


def _stage_routing():
    """Live-router head-to-head (ISSUE 16): the SAME warm workload
    through two schedulers over a fault-free CPU-inner device backend —
    one pinned to the threshold ladder (CBFT_ROUTER=threshold), one on
    the priced argmin — recording throughput, per-flush p99, the priced
    run's windowed regret, and its taken-vs-argmin divergence (the
    route_audit --assert-live judgement, run in-process as the
    acceptance gate). The priced ledger seeds the cpu rung expensive so
    the argmin can engage the moment the single-chip self-EWMA warms."""
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto import decisions as declib
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto.batch import BackendSpec
    from cometbft_tpu.crypto.faults import FaultPlan, install
    from cometbft_tpu.crypto.scheduler import VerifyScheduler
    from cometbft_tpu.crypto.supervisor import BackendSupervisor
    from tools import route_audit

    n = 512
    pks, msgs, sigs = _make_batch(n)
    items = [
        (ed.PubKeyEd25519(pk), m, s) for pk, m, s in zip(pks, msgs, sigs)
    ]
    rounds = 16

    def run(router: str):
        install(name=f"bench-routing-{router}", inner="cpu",
                plan=FaultPlan())
        sup = BackendSupervisor(
            spec=BackendSpec(f"bench-routing-{router}"),
            dispatch_timeout_ms=10_000, breaker_threshold=3,
            audit_pct=0, retry_ms=5,
        )
        ledger = declib.DecisionLedger(
            # price the host rung well above any measured device wall so
            # the argmin engages (and never dodges to cpu) as soon as
            # the single-chip rung has MIN_SELF_OBS observations
            seed=lambda route, bucket: 1e6 if route == "cpu" else None,
        )
        prev = declib.set_default_ledger(ledger)
        sched = VerifyScheduler(
            spec=BackendSpec(f"bench-routing-{router}"), flush_us=300,
            supervisor=sup, router=router,
        )
        sched.start()
        walls = []
        try:
            sched.submit(items[:64], subsystem="bench").result(timeout=60)
            t0 = time.perf_counter()
            for _ in range(rounds):
                t = time.perf_counter()
                ok, mask = sched.submit(
                    items, subsystem="bench"
                ).result(timeout=60)
                walls.append((time.perf_counter() - t) * 1e3)
                assert ok and all(mask)
            total_s = time.perf_counter() - t0
        finally:
            sched.stop()
            declib.set_default_ledger(prev)
            sup.stop()
        walls.sort()
        p99 = walls[min(len(walls) - 1, int(0.99 * len(walls)))]
        return {
            "sigs_per_sec": round(rounds * n / total_s, 1),
            "p99_ms": round(p99, 3),
            "decisions": ledger.snapshot(),
            "scheduler": sched.queue_snapshot(),
        }

    thr = run("threshold")
    pri = run("priced")
    dsnap, qsnap = pri["decisions"], pri["scheduler"]
    problems = route_audit.assert_live(dsnap, qsnap)
    assert not problems, f"route_audit --assert-live: {problems}"
    priced_recs = [
        r for r in dsnap["recent"] if r.get("router") == "priced"
    ]
    # worst fractional taken-vs-argmin divergence over priced records
    # (0.0 = every priced flush took its argmin exactly)
    divergence = 0.0
    for r in priced_recs:
        preds = r.get("predicted_ms") or {}
        feas = r.get("feasible") or {}
        pt = preds.get(r.get("taken"))
        cands = [
            v for c, v in preds.items()
            if isinstance(v, (int, float)) and feas.get(c, False)
        ]
        if isinstance(pt, (int, float)) and cands and min(cands) > 0:
            divergence = max(divergence, pt / min(cands) - 1.0)
    out = {
        "threshold_sigs_per_sec": thr["sigs_per_sec"],
        "priced_sigs_per_sec": pri["sigs_per_sec"],
        "priced_vs_threshold": round(
            pri["sigs_per_sec"] / max(thr["sigs_per_sec"], 1e-9), 3
        ),
        "threshold_p99_ms": thr["p99_ms"],
        "priced_p99_ms": pri["p99_ms"],
        "priced_flushes": len(priced_recs),
        "routing_regret_ms": dsnap["windowed"]["regret_ms"],
        "routing_regret_rate": dsnap["windowed"]["regret_rate"],
        "routing_route_divergence": round(divergence, 4),
        "router_live": qsnap["router"]["live"],
        "router_rollbacks": qsnap["router"]["rollbacks"],
        "live_ok": not problems,
    }
    print(json.dumps(out), flush=True)


def _stage_service():
    """Verify-as-a-service head-to-head (ISSUE 17): 32 clients against
    ONE daemon over a Unix socket, the SAME workload twice — cross-client
    megabatch coalescing on vs off — over the same serialized device-pool
    floor (one lock + a fixed per-dispatch cost, modeling one
    accelerator). Coalescing merges all 32 clients' frames into one flush
    per round and pays the pool floor ONCE; isolated mode pays it per
    client frame. The gain is the aggregate-sigs/sec ratio; the
    acceptance gate is >= 2x (structurally it lands far higher). Also
    proves the compact wire contract end to end: cumulative payload
    bytes per lane over the socket == 128. A quiet single-client pass
    then runs the same wire with cross-process tracing sampled at 1.0
    on every request (client remote-root + wire trace extension +
    server-adopted spans) vs off; the min-of-reps wall delta is the
    propagation overhead, budgeted < 3% like the in-process trace
    stage."""
    import threading

    _maybe_force_cpu()
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto import service as servicelib
    from cometbft_tpu.crypto.scheduler import VerifyScheduler
    from cometbft_tpu.libs import trace as tracelib

    CLIENTS = 32
    LANES = 64
    ROUNDS = 10
    POOL_FLOOR_S = 0.008

    key = ed.gen_priv_key_from_secret(b"bench-service")
    items = []
    for i in range(LANES):
        msg = b"bench service lane %d" % i
        items.append((key.pub_key(), msg, key.sign(msg)))

    pool_mtx = threading.Lock()
    inner = servicelib.host_row_verifier()

    def floor_verifier(rows):
        with pool_mtx:
            time.sleep(POOL_FLOOR_S)
            return inner(rows)

    def run(coalesce: bool) -> dict:
        sched = VerifyScheduler(
            spec="cpu", flush_us=1000, lane_budget=CLIENTS * LANES,
            row_verifier=floor_verifier,
        )
        sock = "/tmp/cbft-bench-svc-%d-%d.sock" % (
            os.getpid(), int(coalesce)
        )
        service = servicelib.VerifyService(
            sched, "unix://" + sock, coalesce=coalesce,
            row_verifier=floor_verifier,
        )
        sched.start()
        service.start()
        clients = [
            servicelib.RemoteVerifier(
                "unix://" + sock, tenant="bench%d" % i, timeout_ms=60_000,
            )
            for i in range(CLIENTS)
        ]
        walls: list = []
        wrong = [0]
        try:
            # warmup: every distinct lane pays its one true host
            # verification here, outside the timed window
            clients[0].submit(items, subsystem="bench").result(timeout=120)

            def client_loop(rv):
                for _ in range(ROUNDS):
                    t0 = time.perf_counter()
                    ok, mask = rv.submit(
                        items, subsystem="bench"
                    ).result(timeout=120)
                    walls.append((time.perf_counter() - t0) * 1e3)
                    if not ok or not all(mask):
                        wrong[0] += 1

            threads = [
                threading.Thread(target=client_loop, args=(rv,))
                for rv in clients
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            total_s = time.perf_counter() - t0
            snap = service.snapshot()
        finally:
            for rv in clients:
                rv.close()
            service.stop()
            sched.stop()
            try:
                os.unlink(sock)
            except OSError:
                pass
        assert wrong[0] == 0, f"{wrong[0]} wrong verdicts over the wire"
        walls.sort()
        p99 = walls[min(len(walls) - 1, int(0.99 * len(walls)))]
        return {
            "sigs_per_sec": round(CLIENTS * ROUNDS * LANES / total_s, 1),
            "p99_ms": round(p99, 3),
            "bytes_per_lane": snap["bytes_per_lane"],
            "inline_dispatches": snap["inline_dispatches"],
        }

    def trace_walls() -> dict:
        """The 32-client run's phase noise swamps a 3%% budget, so the
        trace-propagation delta is measured on the quietest wire path
        instead: ONE server stack (tight flush window, the same
        serialized device-pool floor — the accelerator cost every real
        dispatch pays is the denominator tracing overhead is judged
        against) and TWO sequential-submit clients against it — tracing
        off vs sampled at 1.0 — whose reps interleave, so both arms see
        the same scheduler, the same flush thread, and equally warm
        caches. The server tracer samples locally at 0: only the traced
        client's propagated contexts record server-side (adopted spans
        record unconditionally, and light up the full per-dispatch
        attribution tree), which is exactly the per-request cost the
        extension adds. Min-of-reps wall per arm, like the in-process
        trace stage."""
        SEQ, AB_ROUNDS = 8, 6
        server_tracer = tracelib.Tracer(sample=0.0, buffer=4096)
        sched = VerifyScheduler(
            spec="cpu", flush_us=50, lane_budget=LANES,
            row_verifier=floor_verifier, tracer=server_tracer,
        )
        sock = "/tmp/cbft-bench-svc-tr-%d.sock" % os.getpid()
        service = servicelib.VerifyService(
            sched, "unix://" + sock, coalesce=True,
            row_verifier=floor_verifier,
        )
        sched.start()
        service.start()
        client_tracer = tracelib.Tracer(sample=1.0, buffer=4096)
        rvs = {
            False: servicelib.RemoteVerifier(
                "unix://" + sock, tenant="bench-notrace",
                timeout_ms=60_000,
            ),
            True: servicelib.RemoteVerifier(
                "unix://" + sock, tenant="bench-trace",
                timeout_ms=60_000, tracer=client_tracer,
            ),
        }
        best = {False: None, True: None}
        try:
            for rv in rvs.values():  # warm (+ HELLO handshake), untimed
                rv.submit(items, subsystem="bench").result(timeout=120)
            for _ in range(AB_ROUNDS):
                for arm, rv in rvs.items():
                    t0 = time.perf_counter()
                    for _ in range(SEQ):
                        ok, mask = rv.submit(
                            items, subsystem="bench"
                        ).result(timeout=120)
                        assert ok and all(mask)
                    dt = time.perf_counter() - t0
                    if best[arm] is None or dt < best[arm]:
                        best[arm] = dt
        finally:
            for rv in rvs.values():
                rv.close()
            service.stop()
            sched.stop()
            try:
                os.unlink(sock)
            except OSError:
                pass
        # sanity: the overhead number must cover a LIVE stitched path,
        # not tracing that silently failed to propagate
        names = set()
        for tracer in (client_tracer, server_tracer):
            for tr in tracer.recent(1024):
                for sp in tr["spans"]:
                    names.add(sp["name"])
        assert {"submit", "pack", "wire_wait", "request"} <= names, names
        return best

    iso = run(coalesce=False)
    coal = run(coalesce=True)
    gain = coal["sigs_per_sec"] / max(iso["sigs_per_sec"], 1e-9)
    bpl = coal["bytes_per_lane"]
    assert all(v <= 128.0 for v in bpl.values()), bpl
    assert iso["inline_dispatches"] >= CLIENTS * ROUNDS
    assert coal["inline_dispatches"] == 0
    walls_by_arm = trace_walls()
    off_wall, on_wall = walls_by_arm[False], walls_by_arm[True]
    overhead_pct = (
        max(0.0, (on_wall - off_wall) / off_wall * 100.0)
        if off_wall else 0.0
    )
    out = {
        "service_clients": CLIENTS,
        "service_coalesced_sigs_per_sec": coal["sigs_per_sec"],
        "service_isolated_sigs_per_sec": iso["sigs_per_sec"],
        "service_coalesce_gain": round(gain, 3),
        "service_coalesce_gain_ok": gain >= 2.0,
        "service_p99_ms": coal["p99_ms"],
        "service_isolated_p99_ms": iso["p99_ms"],
        "service_bytes_per_lane": bpl,
        "service_trace_off_ms": round(off_wall * 1e3, 3),
        "service_trace_on_ms": round(on_wall * 1e3, 3),
        "service_trace_overhead_pct": round(overhead_pct, 2),
        "service_trace_overhead_ok": overhead_pct <= 3.0,
    }
    # numbers first, verdicts second: a failed gate still leaves the
    # measurement on stdout (same idiom as the trace stage)
    print(json.dumps(out), flush=True)
    assert gain >= 2.0, f"coalesce gain {gain:.2f} < 2x"
    assert overhead_pct <= 3.0, (
        f"service trace overhead {overhead_pct:.2f}% > 3%"
    )


_COLDBOOT_SCRIPT = r"""
import json, time
t0 = time.perf_counter()
import jax
jax.config.update("jax_compilation_cache_dir", %(cache)r)
# admit EVERY executable to the persistent cache: the point is to
# measure cold-vs-warm cache, not the admission threshold
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
from cometbft_tpu.crypto.tpu import aot, ed25519_batch
from cometbft_tpu.crypto import ed25519 as ed
obs = aot.run_warm_boot(sizes=%(sizes)r)
warm_done = time.perf_counter()
key = ed.gen_priv_key_from_secret(b"coldboot")
pk, msg = key.pub_key().bytes(), b"coldboot message ..............."
sig = key.sign(msg)
reg = aot.default_registry()
before = reg.compile_count
mask = ed25519_batch.verify_batch([pk] * 64, [msg] * 64, [sig] * 64)
t1 = time.perf_counter()
print(json.dumps({
    "to_first_verdict_s": round(t1 - t0, 3),
    "warm_boot_s": round(warm_done - t0, 3),
    "verdict_ok": bool(all(mask)),
    "warm_targets": len(obs),
    "fresh_compiles": sum(1 for o in obs if not o["cached"]),
    "dispatch_compiles_after_warm": reg.compile_count - before,
}))
"""


def _stage_coldboot(sizes=(64,), devices=2):
    """Cold-boot-to-first-verdict (ROADMAP item 2 acceptance): two fresh
    subprocesses boot a small virtual CPU mesh, run the AOT warm boot
    (small buckets only) and verify one 64-sig batch — the first against
    an EMPTY persistent compile cache (every executable pays XLA), the
    second against the cache the first just filled (every executable
    loads). The ratio is the restart tax the warm cache removes; the
    warm run also proves the zero-compile dispatch contract end to end.
    Emits a LOADTIME-style artifact (COLDBOOT.json) beside the bench."""
    import shutil
    import tempfile

    cache = tempfile.mkdtemp(prefix="cbft_coldboot_cache_")
    script = _COLDBOOT_SCRIPT % {"cache": cache, "sizes": list(sizes)}
    env = dict(os.environ)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)  # the tmp cache must win
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices}",
        "CBFT_TPU_PROBE": "0",
    })
    out = {"buckets": list(sizes), "devices": devices}

    def boot(label):
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", script], env=env,
                capture_output=True, text=True, timeout=540,
            )
        except subprocess.TimeoutExpired:
            return {"error": "timeout"}
        rec = None
        for line in (proc.stdout or "").strip().splitlines():
            try:
                rec = json.loads(line)
            except Exception:  # noqa: BLE001
                continue
        if rec is None:
            return {
                "error": (proc.stderr or "no output")[-300:].replace(
                    "\n", " | "
                )
            }
        rec["subprocess_wall_s"] = round(time.perf_counter() - t0, 3)
        return rec

    try:
        out["cold"] = boot("cold")
        out["warm"] = boot("warm")
        cold_s = out["cold"].get("to_first_verdict_s")
        warm_s = out["warm"].get("to_first_verdict_s")
        if cold_s and warm_s:
            out["speedup_to_first_verdict"] = round(cold_s / warm_s, 2)
            out["meets_5x"] = cold_s / warm_s >= 5.0
        try:
            artifact = dict(out)
            artifact["measured_at"] = time.time()
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "COLDBOOT.json"
            )
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(artifact, fh, indent=1, sort_keys=True)
        except OSError:
            pass
    finally:
        shutil.rmtree(cache, ignore_errors=True)
    print(json.dumps(out), flush=True)


def _set_cache():
    import jax

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)


def _run_stage(stage: str, env_extra: dict, timeout: float):
    """→ (parsed_json | None, diagnostic_str). Reads the LAST parseable
    stdout line, so stages that print incrementally keep their partial
    results even when they hit the timeout."""
    env = dict(os.environ)
    env.update(env_extra)
    timed_out = False
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--stage", stage],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        stdout, rc = proc.stdout or "", proc.returncode
    except subprocess.TimeoutExpired as exc:
        stdout = (
            exc.stdout.decode() if isinstance(exc.stdout, bytes) else exc.stdout
        ) or ""
        rc, timed_out = -1, True
    last = None
    for line in stdout.strip().splitlines():
        try:
            last = json.loads(line)
        except Exception:  # noqa: BLE001
            continue
    if timed_out:
        if last is not None:
            last["partial"] = f"timeout after {timeout}s"
            return last, "partial"
        return None, f"timeout after {timeout}s"
    if rc != 0:
        tail = (proc.stderr or stdout or "")[-400:].replace("\n", " | ")
        if last is not None:  # keep partial results, but mark the crash
            last["error"] = f"rc={rc}: {tail}"
        return last, f"rc={rc}: {tail}"
    if last is None:
        return None, "unparseable stdout"
    return last, "ok"


def _retry_stage(stage: str, env_extra: dict, timeout: float, budget_s: float):
    """Retry a failing stage with doubling backoff until `budget_s` of
    wall clock is spent (first attempt always runs). A wedged TPU tunnel
    often recovers within minutes; one cheap enumerate attempt per bench
    run threw away whole sessions that a later retry would have saved.
    → (parsed, diag, attempts)."""
    deadline = time.monotonic() + max(budget_s, 0.0)
    delay = 5.0
    attempts = 0
    while True:
        attempts += 1
        parsed, diag = _run_stage(stage, env_extra, timeout)
        if parsed is not None:
            return parsed, diag, attempts
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None, diag, attempts
        time.sleep(min(delay, remaining))
        delay = min(delay * 2, 120.0)


_HISTORY_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_onchip_history.jsonl"
)


def _last_onchip_session():
    """Most recent BENCH_onchip_history.jsonl record with a real on-chip
    run (stages.tpu_run.sigs_per_sec present), or None. Embedded in the
    output when the tunnel is wedged so a CPU-fallback run still carries
    the latest measured on-chip numbers instead of a bare CPU headline."""
    try:
        with open(_HISTORY_PATH, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except Exception:  # noqa: BLE001
            continue
        run = (rec.get("stages") or {}).get("tpu_run")
        if isinstance(run, dict) and run.get("sigs_per_sec"):
            return rec
    return None


def _append_history(record, stage=None):
    """Append a record to BENCH_onchip_history.jsonl — the ledger
    tools/bench_history.py's regression sentinel reads. With `stage`,
    wraps a bare stage dict as a `bench_stage_<name>` record (same
    shape as `bench_history.py --append --stage`), so the
    platform-neutral stages leave comparable evidence even when the
    session dies at the TPU tunnel. BENCH_HISTORY=0 disables all
    appends (e.g. a driver that archives the full record itself).
    Best-effort: a read-only checkout must not fail the bench."""
    if os.environ.get("BENCH_HISTORY", "1") == "0":
        return
    if stage is not None:
        record = {
            "metric": f"bench_stage_{stage}",
            "unit": "mixed",
            "stages": {stage: record},
        }
    try:
        with open(_HISTORY_PATH, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(record, sort_keys=True) + "\n")
    except OSError:
        pass


def main():
    stages = {}
    cpu_serial = bench_cpu_serial()
    stages["cpu_serial_sigs_per_sec"] = round(cpu_serial, 1)
    cpu_batch = bench_cpu_batch()
    stages["cpu_batch64_sigs_per_sec"] = round(cpu_batch, 1)
    stages["cpu_parallel_sigs_per_sec"] = round(bench_cpu_parallel(), 1)
    stages["cpu_ncores"] = os.cpu_count() or 1

    backend = "tpu"
    result = None
    retry_budget = float(os.environ.get("BENCH_DEVICE_RETRY_BUDGET_S", "600"))
    for name, timeout in (("devices", 120), ("compile", 600), ("run", 600)):
        if name == "devices":
            parsed, diag, attempts = _retry_stage(
                name, _STAGE_ENV_TPU, timeout, retry_budget
            )
            if attempts > 1:
                stages["tpu_devices_attempts"] = attempts
        else:
            parsed, diag = _run_stage(name, _STAGE_ENV_TPU, timeout)
        stages[f"tpu_{name}"] = parsed if parsed is not None else diag
        if parsed is None:
            break
        if name == "run" and "sigs_per_sec" in parsed:
            result = parsed["sigs_per_sec"]

    if result is not None:
        for name, timeout in (
            ("p50", 600), ("variants", 600), ("breakdown", 600),
            ("scheduler", 600),
        ):
            parsed, diag = _run_stage(name, _STAGE_ENV_TPU, timeout)
            stages[f"tpu_{name}"] = parsed if parsed is not None else diag
            if name == "breakdown" and parsed is not None:
                # wire-path phase numbers (prepare/transfer/compute ms)
                # join the regression ledger so the sentinel pages on
                # link regressions, not just throughput ones
                _append_history(parsed, stage="tpu_breakdown")

    # CPU-side p50s always run (serial CPU verifier — no kernel compile):
    # BASELINE.md's comparison needs both backends from one bench run
    parsed, diag = _run_stage("p50", _STAGE_ENV_CPU, 600)
    stages["cpu_p50"] = parsed if parsed is not None else diag

    # supervisor degraded-mode + recovery-latency numbers (CPU-inner
    # faulty backend — platform-neutral, so it always runs)
    parsed, diag = _run_stage("supervisor", _STAGE_ENV_CPU, 300)
    stages["supervisor"] = parsed if parsed is not None else diag

    # degradation-ladder numbers: retry-rung throughput bound, triage
    # pass-count bound, chaos-smoke divergence — platform-neutral
    parsed, diag = _run_stage("degraded", _STAGE_ENV_CPU, 300)
    stages["degraded"] = parsed if parsed is not None else diag
    if parsed is not None:
        _append_history(parsed, stage="degraded")

    # QoS overload numbers: consensus p99 through the flood (ladder on
    # vs CBFT_QOS_CLASSES=off), shed/drop/brownout counters —
    # platform-neutral (CPU-inner faulty backend)
    parsed, diag = _run_stage("overload", _STAGE_ENV_CPU, 300)
    stages["overload"] = parsed if parsed is not None else diag
    if parsed is not None:
        _append_history(parsed, stage="overload")

    # decision-plane report card: prediction accuracy, regret, and the
    # ledger/scheduler reconciliation (platform-neutral)
    parsed, diag = _run_stage("decisions", _STAGE_ENV_CPU, 300)
    stages["decisions"] = parsed if parsed is not None else diag
    if parsed is not None:
        _append_history(parsed, stage="decisions")

    # live-router head-to-head: threshold vs priced argmin through the
    # same workload (throughput, p99, regret, taken-vs-argmin
    # divergence) — platform-neutral (CPU-inner faulty backend)
    parsed, diag = _run_stage("routing", _STAGE_ENV_CPU, 300)
    stages["routing"] = parsed if parsed is not None else diag
    if parsed is not None:
        _append_history(parsed, stage="routing")

    # verify-as-a-service: 32 clients against one daemon over a Unix
    # socket — cross-client megabatch coalescing vs per-client isolated
    # dispatch over the same serialized device-pool floor, plus the
    # compact-wire bytes/lane proof (platform-neutral, jax-free)
    parsed, diag = _run_stage("service", _STAGE_ENV_CPU, 600)
    stages["service"] = parsed if parsed is not None else diag
    if parsed is not None:
        _append_history(parsed, stage="service")

    # tracing overhead budget (<3% on the scheduler stage) + per-stage
    # dispatch breakdown — platform-neutral, so it always runs
    parsed, diag = _run_stage("trace", _STAGE_ENV_CPU, 300)
    stages["trace"] = parsed if parsed is not None else diag

    # cold-boot-to-first-verdict, cold vs warm persistent cache, on the
    # virtual CPU mesh — the restart tax the AOT warm boot removes
    # (platform-neutral; the stage runs its own fresh subprocesses)
    parsed, diag = _run_stage("coldboot", _STAGE_ENV_CPU, 1200)
    stages["coldboot"] = parsed if parsed is not None else diag
    if parsed is not None:
        _append_history(parsed, stage="coldboot")

    # sharded-megabatch routing: the 10k-commit megabatch on the 8-way
    # virtual mesh vs the same kernel single-chip — the two device-side
    # routes the scheduler crossover picks between (platform-neutral);
    # the appended record puts sharded throughput under the sentinel
    parsed, diag = _run_stage("sharded", _STAGE_ENV_SHARDED, 900)
    stages["sharded"] = parsed if parsed is not None else diag
    if parsed is not None:
        _append_history(parsed, stage="sharded")

    # adversarial-committee ladder: p50/p99 commit-verify per committee
    # size (128 -> 1k) under a byzantine storm, zero-wrong-verdict gate
    # riding the sentinel (platform-neutral, CPU-inner faulty backend)
    parsed, diag = _run_stage("adversary", _STAGE_ENV_CPU, 600)
    stages["adversary"] = parsed if parsed is not None else diag
    if parsed is not None:
        _append_history(parsed, stage="adversary")

    # HA verify fleet: failover gap p99 + rolling zero-CPU proof +
    # fleet-vs-single aggregate throughput across three replicated
    # daemons (platform-neutral, CPU-inner floor backend)
    parsed, diag = _run_stage("ha", _STAGE_ENV_CPU, 600)
    stages["ha"] = parsed if parsed is not None else diag
    if parsed is not None:
        _append_history(parsed, stage="ha")

    last_onchip = None
    if result is None:
        # TPU unavailable — same kernel on the host CPU platform so the
        # pipeline still yields a measured number + full diagnostics.
        backend = "cpu-fallback"
        parsed, diag = _run_stage("run", _STAGE_ENV_CPU, 600)
        stages["cpu_fallback_run"] = parsed if parsed is not None else diag
        if parsed is not None and "sigs_per_sec" in parsed:
            result = parsed["sigs_per_sec"]
        # scheduler coalescing numbers still matter off-chip: the
        # contract (fewer dispatches than callers) is platform-neutral
        parsed, diag = _run_stage("scheduler", _STAGE_ENV_CPU, 600)
        stages["cpu_scheduler"] = parsed if parsed is not None else diag
        prior = _last_onchip_session()
        if prior is not None:
            last_onchip = {
                "label": "latest recorded on-chip session "
                         "(TPU tunnel unavailable this run)",
                "value": prior.get("value"),
                "unit": prior.get("unit"),
                "tpu_run": (prior.get("stages") or {}).get("tpu_run"),
            }

    if result is None:
        # last resort: the serial number measured above — the bench's
        # contract is that the value is NEVER 0.0 (round-3 regression)
        backend = "cpu-serial-floor"
        result = cpu_serial

    value = round(result, 1)
    best_cpu = max(
        cpu_serial, cpu_batch, stages["cpu_parallel_sigs_per_sec"]
    )
    out = {
        "metric": f"ed25519_batch_verify_throughput_{backend}",
        "value": value,
        "unit": "sigs/sec",
        # the north-star comparison: vs the CPU BATCH baseline
        "vs_baseline": round(value / cpu_batch, 3) if cpu_batch else 0.0,
        "vs_serial": round(value / cpu_serial, 3) if cpu_serial else 0.0,
        # the honest >=20x denominator (docstring): the BEST
        # CPU number measured this run, whichever path wins
        "vs_best_cpu": round(value / best_cpu, 3) if best_cpu else 0.0,
        "stages": stages,
    }
    if last_onchip is not None:
        out["last_onchip"] = last_onchip
    # full-record append is opt-in: the default ledger rows are written
    # by the bench driver, and a double entry would skew the sentinel's
    # rolling baseline
    if os.environ.get("BENCH_HISTORY_FULL") == "1":
        _append_history(out)
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        {
            "devices": _stage_devices,
            "compile": _stage_compile,
            "run": _stage_run,
            "p50": _stage_p50,
            "variants": _stage_variants,
            "breakdown": _stage_breakdown,
            "scheduler": _stage_scheduler,
            "supervisor": _stage_supervisor,
            "degraded": _stage_degraded,
            "overload": _stage_overload,
            "adversary": _stage_adversary,
            "ha": _stage_ha,
            "sharded": _stage_sharded,
            "decisions": _stage_decisions,
            "routing": _stage_routing,
            "service": _stage_service,
            "trace": _stage_trace,
            "coldboot": _stage_coldboot,
        }[sys.argv[2]]()
    else:
        main()
