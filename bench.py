"""Headline benchmark: batched Ed25519 verification on TPU vs the
reference's CPU paths, plus the north-star VerifyCommit latencies.

The reference (dymensionxyz/cometbft) verifies every commit signature one
at a time on one core (types/validator_set.go:685-707 → ed25519.go:148).
BASELINE.md:26-36 demands measurement against BOTH that serial loop and a
CPU *batch* verifier (64-sig batches through the BatchVerifier boundary —
the strongest CPU batch implementation available here), plus VerifyCommit
p50 at 150 and 10k validators on both backends.

Staged preflight (each stage subprocess-isolated with its own timeout so a
wedged TPU runtime can never take the bench down with it):
  1. device enumerate            (120 s)
  2. jit lower+compile, batch=64 (600 s)
  3. timed full run + sweep      (600 s)
  4. VerifyCommit p50s + merkle  (600 s)
If a TPU stage fails, fall back to the same kernel on the virtual CPU
platform so a number is ALWAYS produced; every stage's outcome is recorded
in the "stages" field of the JSON line for diagnosability.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"vs_serial", "stages"}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 4096
SWEEP = (1024, 4096, 8192, 16384)
_STAGE_ENV_TPU = {}  # inherit ambient (axon) platform
_STAGE_ENV_CPU = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_FORCE_CPU": "1",
}


def _make_batch(n: int, msg_len: int = 120):
    from cometbft_tpu.crypto import ed25519 as ed

    rng = np.random.default_rng(42)
    keys = [
        ed.gen_priv_key_from_secret(bytes([i & 0xFF, i >> 8]))
        for i in range(min(n, 128))
    ]
    pks, msgs, sigs = [], [], []
    for i in range(n):
        k = keys[i % len(keys)]
        m = rng.bytes(msg_len)  # ~ a canonical vote's sign-bytes size
        pks.append(k.pub_key().bytes())
        msgs.append(m)
        sigs.append(k.sign(m))
    return pks, msgs, sigs


def _make_commit(n_vals: int):
    """A real Commit over n_vals validators + its ValidatorSet."""
    from cometbft_tpu.proto.gogo import Timestamp
    from cometbft_tpu.types import test_util

    vals, privs = test_util.deterministic_validator_set(n_vals, 10)
    bid = test_util.make_block_id()
    commit = test_util.make_commit(
        bid, 5, 0, vals, privs, "bench-chain", now=Timestamp(1_700_000_000, 0)
    )
    return vals, commit, bid


def bench_cpu_serial(n: int = 512) -> float:
    from cometbft_tpu.crypto import ed25519 as ed

    pks, msgs, sigs = _make_batch(n)
    keys = [ed.PubKeyEd25519(pk) for pk in pks]
    t0 = time.perf_counter()
    for k, m, s in zip(keys, msgs, sigs):
        assert k.verify_signature(m, s)
    dt = time.perf_counter() - t0
    return n / dt


def bench_cpu_batch(n: int = 1024, batch_size: int = 64) -> float:
    """The BASELINE.md CPU batch baseline: 64-sig batches through the
    BatchVerifier boundary (cpu backend)."""
    from cometbft_tpu.crypto import batch as cryptobatch
    from cometbft_tpu.crypto import ed25519 as ed

    pks, msgs, sigs = _make_batch(n)
    keys = [ed.PubKeyEd25519(pk) for pk in pks]
    t0 = time.perf_counter()
    for start in range(0, n, batch_size):
        bv = cryptobatch.new_batch_verifier("cpu")
        for i in range(start, min(start + batch_size, n)):
            bv.add(keys[i], msgs[i], sigs[i])
        ok, _ = bv.verify()
        assert ok
    dt = time.perf_counter() - t0
    return n / dt


def bench_verify_commit_p50(n_vals: int, backend: str, reps: int) -> float:
    """VerifyCommit wall-time p50 (ms) at n_vals validators."""
    vals, commit, bid = _make_commit(n_vals)
    times = []
    # warmup (compile for the tpu backend)
    vals.verify_commit("bench-chain", bid, 5, commit, backend=backend)
    for _ in range(reps):
        t0 = time.perf_counter()
        vals.verify_commit("bench-chain", bid, 5, commit, backend=backend)
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2] * 1e3


# ---------------------------------------------------------------------------
# subprocess stages (run with: python bench.py --stage <name>)
# ---------------------------------------------------------------------------


def _maybe_force_cpu():
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax

        # env vars alone are too late if sitecustomize pre-imported jax
        jax.config.update("jax_platforms", "cpu")


def _stage_devices():
    _maybe_force_cpu()
    import jax

    devs = jax.devices()
    print(json.dumps({"n": len(devs), "platform": devs[0].platform}))


def _stage_compile():
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto.tpu import ed25519_batch

    pks, msgs, sigs = _make_batch(64)
    t0 = time.perf_counter()
    out = ed25519_batch.verify_batch(pks, msgs, sigs)
    assert all(out), "preflight batch must verify"
    print(json.dumps({"compile_and_run_s": round(time.perf_counter() - t0, 2)}))


def _stage_run():
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto.tpu import ed25519_batch

    out = {}
    best_overall = 0.0
    sweep = SWEEP
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # fallback exists to guarantee A number — the big shapes take
        # many minutes to compile on the host platform and would blow the
        # stage timeout
        sweep = (1024,)
    for batch in sweep:
        pks, msgs, sigs = _make_batch(batch)
        res = ed25519_batch.verify_batch(pks, msgs, sigs)  # warmup/compile
        assert all(res), "benchmark batch must verify"
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            ed25519_batch.verify_batch(pks, msgs, sigs)
            best = min(best, time.perf_counter() - t0)
        rate = batch / best
        out[str(batch)] = round(rate, 1)
        best_overall = max(best_overall, rate)
    print(json.dumps({"sigs_per_sec": best_overall, "sweep": out}))


def _stage_p50():
    _maybe_force_cpu()
    _set_cache()
    out = {}
    backend = "cpu" if os.environ.get("BENCH_FORCE_CPU") == "1" else "tpu"
    out[f"verify_commit_p50_ms_150_{backend}"] = round(
        bench_verify_commit_p50(150, backend, reps=9), 2
    )
    out[f"verify_commit_p50_ms_10k_{backend}"] = round(
        bench_verify_commit_p50(10_000, backend, reps=3), 2
    )
    # 10k-validator mega-set Merkle root (ValidatorSet.Hash)
    from cometbft_tpu.types import test_util

    vals, _ = test_util.deterministic_validator_set(10_000, 10)
    items = [v.bytes() for v in vals.validators]
    if backend == "tpu":
        from cometbft_tpu.crypto.tpu import merkle as tpu_merkle

        tpu_merkle.hash_from_byte_slices(items, force_device=True)  # warm
        t0 = time.perf_counter()
        tpu_merkle.hash_from_byte_slices(items, force_device=True)
        out["merkle_10k_root_ms_tpu"] = round((time.perf_counter() - t0) * 1e3, 2)
    from cometbft_tpu.crypto import merkle as cpu_merkle

    t0 = time.perf_counter()
    cpu_merkle.hash_from_byte_slices(items)
    out["merkle_10k_root_ms_cpu"] = round((time.perf_counter() - t0) * 1e3, 2)
    print(json.dumps(out))


def _set_cache():
    import jax

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)


def _run_stage(stage: str, env_extra: dict, timeout: float):
    """→ (parsed_json | None, diagnostic_str)."""
    env = dict(os.environ)
    env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--stage", stage],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout}s"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "")[-400:].replace("\n", " | ")
        return None, f"rc={proc.returncode}: {tail}"
    try:
        last = proc.stdout.strip().splitlines()[-1]
        return json.loads(last), "ok"
    except Exception as exc:  # noqa: BLE001
        return None, f"unparseable stdout: {exc}"


def main():
    stages = {}
    cpu_serial = bench_cpu_serial()
    stages["cpu_serial_sigs_per_sec"] = round(cpu_serial, 1)
    cpu_batch = bench_cpu_batch()
    stages["cpu_batch64_sigs_per_sec"] = round(cpu_batch, 1)

    backend = "tpu"
    result = None
    for name, timeout in (("devices", 120), ("compile", 600), ("run", 600)):
        parsed, diag = _run_stage(name, _STAGE_ENV_TPU, timeout)
        stages[f"tpu_{name}"] = diag if parsed is None else parsed
        if parsed is None:
            break
        if name == "run":
            result = parsed["sigs_per_sec"]

    if result is not None:
        parsed, diag = _run_stage("p50", _STAGE_ENV_TPU, 600)
        stages["tpu_p50"] = diag if parsed is None else parsed

    # CPU-side p50s always run (serial CPU verifier — no kernel compile):
    # BASELINE.md's comparison needs both backends from one bench run
    parsed, diag = _run_stage("p50", _STAGE_ENV_CPU, 600)
    stages["cpu_p50"] = diag if parsed is None else parsed

    if result is None:
        # TPU unavailable — same kernel on the host CPU platform so the
        # pipeline still yields a measured number + full diagnostics.
        backend = "cpu-fallback"
        parsed, diag = _run_stage("run", _STAGE_ENV_CPU, 900)
        stages["cpu_fallback_run"] = diag if parsed is None else parsed
        if parsed is not None:
            result = parsed["sigs_per_sec"]

    value = round(result, 1) if result is not None else 0.0
    print(
        json.dumps(
            {
                "metric": f"ed25519_batch_verify_throughput_{backend}",
                "value": value,
                "unit": "sigs/sec",
                # the north-star comparison: vs the CPU BATCH baseline
                "vs_baseline": round(value / cpu_batch, 3) if cpu_batch else 0.0,
                "vs_serial": round(value / cpu_serial, 3) if cpu_serial else 0.0,
                "stages": stages,
            }
        )
    )


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        {
            "devices": _stage_devices,
            "compile": _stage_compile,
            "run": _stage_run,
            "p50": _stage_p50,
        }[sys.argv[2]]()
    else:
        main()
