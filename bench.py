"""Headline benchmark: batched Ed25519 verification throughput on TPU vs the
reference's serial CPU path.

The reference (dymensionxyz/cometbft) verifies every commit signature one at
a time on one core (types/validator_set.go:685-707 → ed25519.go:148).
Baseline here = that same serial loop on this host's CPU (the strongest
single-core implementation available). Value = sigs/sec through the JAX
batch kernel on the attached chip.

Staged preflight (each stage subprocess-isolated with its own timeout so a
wedged TPU runtime can never take the bench down with it):
  1. device enumerate            (120 s)
  2. jit lower+compile, batch=64 (600 s)
  3. timed full run              (600 s)
If a TPU stage fails, fall back to the same kernel on the virtual CPU
platform so a number is ALWAYS produced; every stage's outcome is recorded
in the "stages" field of the JSON line for diagnosability.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "stages"}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 2048
_STAGE_ENV_TPU = {}  # inherit ambient (axon) platform
_STAGE_ENV_CPU = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_FORCE_CPU": "1",
}


def _make_batch(n: int):
    from cometbft_tpu.crypto import ed25519 as ed

    rng = np.random.default_rng(42)
    keys = [
        ed.gen_priv_key_from_secret(bytes([i & 0xFF, i >> 8]))
        for i in range(min(n, 128))
    ]
    pks, msgs, sigs = [], [], []
    for i in range(n):
        k = keys[i % len(keys)]
        m = rng.bytes(120)  # ~ a canonical vote's sign-bytes size
        pks.append(k.pub_key().bytes())
        msgs.append(m)
        sigs.append(k.sign(m))
    return pks, msgs, sigs


def bench_cpu_serial(n: int = 512) -> float:
    from cometbft_tpu.crypto import ed25519 as ed

    pks, msgs, sigs = _make_batch(n)
    keys = [ed.PubKeyEd25519(pk) for pk in pks]
    t0 = time.perf_counter()
    for k, m, s in zip(keys, msgs, sigs):
        assert k.verify_signature(m, s)
    dt = time.perf_counter() - t0
    return n / dt


# ---------------------------------------------------------------------------
# subprocess stages (run with: python bench.py --stage <name>)
# ---------------------------------------------------------------------------


def _maybe_force_cpu():
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax

        # env vars alone are too late if sitecustomize pre-imported jax
        jax.config.update("jax_platforms", "cpu")


def _stage_devices():
    _maybe_force_cpu()
    import jax

    devs = jax.devices()
    print(json.dumps({"n": len(devs), "platform": devs[0].platform}))


def _stage_compile():
    _maybe_force_cpu()
    _set_cache()
    import jax.numpy as jnp

    from cometbft_tpu.crypto.tpu import ed25519_batch

    pks, msgs, sigs = _make_batch(64)
    t0 = time.perf_counter()
    out = ed25519_batch.verify_batch(pks, msgs, sigs)
    assert all(out), "preflight batch must verify"
    print(json.dumps({"compile_and_run_s": round(time.perf_counter() - t0, 2)}))


def _stage_run():
    _maybe_force_cpu()
    _set_cache()
    from cometbft_tpu.crypto.tpu import ed25519_batch

    pks, msgs, sigs = _make_batch(BATCH)
    out = ed25519_batch.verify_batch(pks, msgs, sigs)  # warmup/compile
    assert all(out), "benchmark batch must verify"
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        ed25519_batch.verify_batch(pks, msgs, sigs)
        best = min(best, time.perf_counter() - t0)
    print(json.dumps({"sigs_per_sec": len(pks) / best, "batch": len(pks)}))


def _set_cache():
    import jax

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)


def _run_stage(stage: str, env_extra: dict, timeout: float):
    """→ (parsed_json | None, diagnostic_str)."""
    env = dict(os.environ)
    env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--stage", stage],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout}s"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "")[-400:].replace("\n", " | ")
        return None, f"rc={proc.returncode}: {tail}"
    try:
        last = proc.stdout.strip().splitlines()[-1]
        return json.loads(last), "ok"
    except Exception as exc:  # noqa: BLE001
        return None, f"unparseable stdout: {exc}"


def main():
    stages = {}
    cpu_serial = bench_cpu_serial()
    stages["cpu_serial_sigs_per_sec"] = round(cpu_serial, 1)

    backend = "tpu"
    result = None
    for name, timeout in (("devices", 120), ("compile", 600), ("run", 600)):
        parsed, diag = _run_stage(name, _STAGE_ENV_TPU, timeout)
        stages[f"tpu_{name}"] = diag if parsed is None else parsed
        if parsed is None:
            break
        if name == "run":
            result = parsed["sigs_per_sec"]

    if result is None:
        # TPU unavailable — same kernel on the host CPU platform so the
        # pipeline still yields a measured number + full diagnostics.
        backend = "cpu-fallback"
        parsed, diag = _run_stage("run", _STAGE_ENV_CPU, 900)
        stages["cpu_fallback_run"] = diag if parsed is None else parsed
        if parsed is not None:
            result = parsed["sigs_per_sec"]

    value = round(result, 1) if result is not None else 0.0
    print(
        json.dumps(
            {
                "metric": f"ed25519_batch_verify_throughput_{backend}",
                "value": value,
                "unit": "sigs/sec",
                "vs_baseline": round(value / cpu_serial, 3) if cpu_serial else 0.0,
                "stages": stages,
            }
        )
    )


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        {
            "devices": _stage_devices,
            "compile": _stage_compile,
            "run": _stage_run,
        }[sys.argv[2]]()
    else:
        main()
