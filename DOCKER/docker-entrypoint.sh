#!/bin/sh
# Reference: DOCKER/docker-entrypoint.sh — init the home on first boot,
# then exec the node so signals reach it directly.
set -e
if [ ! -f "/cometbft/config/genesis.json" ]; then
    python -m cometbft_tpu --home /cometbft init --chain-id "${CHAIN_ID:-dockerchain}"
fi
exec python -m cometbft_tpu --home /cometbft "$@"
